"""Network chaos plane tests (inference/transport.py wire-fault
injection + retry/cid protocol, inference/fleet_worker.py exactly-once
dedup, inference/fleet.py circuit breaker):

- ``WireFaultInjector`` plan semantics: exact indices, ``times`` /
  ``every`` / ``rate`` triggers, ops/replica filters that consume no
  index, seeded replayability.
- Frame-parser robustness as a property: a frame stream split at EVERY
  byte offset — and fully coalesced, and one byte at a time — parses to
  the same frames, with interleaved heartbeats consumed inline.
- The timeout-desync regression: a response arriving one byte at a time
  ACROSS the call deadline leaves a partial frame buffered; the next
  call must discard the late reply by call id and resynchronize.
- Channel retry: idempotent calls retry on ``RpcTimeout`` under a fresh
  cid with the SAME idempotency key; non-idempotent calls never do.
- Worker dedup: a duplicated cid resends the cached response verbatim
  (no re-execution); a replayed ikey returns the recorded outcome
  flagged ``dup`` (exactly-once mutation semantics).
- ``CircuitBreaker`` state machine on a fake clock: trip threshold,
  half-open probe cycle, doubling cooldowns, flap hysteresis.
- Vocabulary lockstep: the wire fault sites are the frozen tail of
  ``runtime/resilience.py``'s FAULT_SITES.
- slow: subprocess end-to-end exactly-once proof (dropped admission
  ack) and the breaker/liveness composition — a tripped breaker fences
  WITHOUT killing, exempt from heartbeat death, exactly ONE incident.
"""

import importlib.util
import json
import os
import socket
import struct
import threading
import time

import numpy as np
import pytest

from deepspeed_tpu.inference.fleet import CircuitBreaker, FleetRouter
from deepspeed_tpu.inference.fleet_worker import (FleetWorker,
                                                  tiny_engine_factory)
from deepspeed_tpu.inference.transport import (RpcChannel, RpcTimeout,
                                               TransportError,
                                               WIRE_FAULT_SITES,
                                               WireFaultInjector,
                                               pack_value, send_frame)
from deepspeed_tpu.monitor.telemetry import Telemetry
from deepspeed_tpu.runtime.config import TelemetryConfig
from deepspeed_tpu.runtime.resilience import RetryPolicy

SPEC = {"factory":
        "deepspeed_tpu.inference.fleet_worker:tiny_engine_factory",
        "kwargs": {}}


def _load_checker():
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    path = os.path.join(repo, "scripts", "check_telemetry_schema.py")
    spec = importlib.util.spec_from_file_location("_chaos_checker", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ----------------------------------------------------------------------
# WireFaultInjector plan semantics
# ----------------------------------------------------------------------
def test_injector_fires_at_exact_indices():
    inj = WireFaultInjector({"wire_send": {"drop_at": [1, 3]}})
    acts = [inj.plan("wire_send", op="step") for _ in range(5)]
    assert acts == [None, "drop", None, "drop", None]
    assert inj.calls("wire_send") == 5
    assert inj.fired("wire_send") == 2


def test_injector_op_filter_consumes_no_index():
    """Filtered-out invocations must not advance the site counter, so a
    plan aimed at one op stays deterministic no matter how much
    unrelated traffic interleaves."""
    inj = WireFaultInjector({"wire_send": {"drop_at": [0],
                                           "ops": ["add_request"]}})
    for _ in range(10):                       # unrelated chatter
        assert inj.plan("wire_send", op="step") is None
    assert inj.calls("wire_send") == 0        # nothing consumed
    assert inj.plan("wire_send", op="add_request") == "drop"


def test_injector_replica_filter_is_independent():
    inj = WireFaultInjector({"rpc_timeout": {"timeout_at": [0],
                                             "replicas": ["r1"]}})
    assert inj.plan("rpc_timeout", op="step", peer="r0") is None
    assert inj.calls("rpc_timeout") == 0
    assert inj.plan("rpc_timeout", op="step", peer="r1") == "timeout"


def test_injector_times_and_every_triggers():
    inj = WireFaultInjector({"rpc_timeout": {"action": "timeout",
                                             "times": 2}})
    acts = [inj.plan("rpc_timeout") for _ in range(4)]
    assert acts == ["timeout", "timeout", None, None]
    inj = WireFaultInjector({"wire_recv": {"action": "drop", "every": 3}})
    acts = [inj.plan("wire_recv") for _ in range(7)]
    assert acts == [None, None, "drop", None, None, "drop", None]


def test_injector_rate_is_seed_deterministic():
    spec = {"wire_send": {"action": "drop", "rate": 0.5}}
    plans = []
    for _ in range(2):
        inj = WireFaultInjector(spec, seed=7)
        plans.append([inj.plan("wire_send") for _ in range(40)])
    assert plans[0] == plans[1]               # same seed, same campaign
    assert "drop" in plans[0] and None in plans[0]
    other = WireFaultInjector(spec, seed=8)
    assert [other.plan("wire_send") for _ in range(40)] != plans[0]


def test_injector_rejects_unknown_site_and_action():
    with pytest.raises(ValueError):
        WireFaultInjector({"not_a_site": {"drop_at": [0]}})
    inj = WireFaultInjector({"wire_send": {"action": "explode",
                                           "times": 1}})
    with pytest.raises(ValueError):
        inj.plan("wire_send")
    with pytest.raises(ValueError):
        WireFaultInjector({}).plan("not_a_site")


def test_injector_from_config_empty_is_none():
    assert WireFaultInjector.from_config(None) is None
    assert WireFaultInjector.from_config({}) is None
    assert WireFaultInjector.from_config(
        {"wire_send": {"drop_at": [0]}}) is not None


def test_injector_seed_rides_spec():
    inj = WireFaultInjector({"seed": 42, "wire_send": {"drop_at": [0]}})
    assert inj.seed == 42
    assert "seed" not in inj.spec


# ----------------------------------------------------------------------
# frame parser as a property: every split of the byte stream parses the
# same (satellite: property-style fragmentation test)
# ----------------------------------------------------------------------
def _frame_bytes(obj):
    data = json.dumps(pack_value(obj), separators=(",", ":")).encode()
    return struct.pack(">I", len(data)) + data


def _parse_channel():
    ch = RpcChannel(None, clock=lambda: 0.0)
    return ch


def _stream_and_expected():
    frames = [{"kind": "resp", "cid": 0, "val": "a"},
              {"kind": "hb", "seq": 0, "rid": "r0"},
              {"kind": "resp", "cid": 1, "val": "bb"},
              {"kind": "hb", "seq": 1, "rid": "r0"},
              {"kind": "resp", "cid": 2, "val": "ccc"}]
    stream = b"".join(_frame_bytes(f) for f in frames)
    resps = [f for f in frames if f["kind"] == "resp"]
    return stream, resps


def test_frame_parser_every_byte_offset():
    """Splitting the stream at ANY byte boundary — inside a length
    prefix, inside a JSON body, between frames — must yield exactly the
    same frames as one coalesced delivery."""
    stream, resps = _stream_and_expected()
    for cut in range(1, len(stream)):
        ch = _parse_channel()
        ch._buf.extend(stream[:cut])
        ch._parse()
        ch._buf.extend(stream[cut:])
        ch._parse()
        assert list(ch._inbox) == resps, f"diverged at offset {cut}"
        assert ch.hb_seq == 1


def test_frame_parser_one_byte_at_a_time_and_coalesced():
    stream, resps = _stream_and_expected()
    drip = _parse_channel()
    for i in range(len(stream)):
        drip._buf.extend(stream[i:i + 1])
        drip._parse()
    whole = _parse_channel()
    whole._buf.extend(stream)
    whole._parse()
    assert list(drip._inbox) == list(whole._inbox) == resps


def test_frame_parser_heartbeats_never_reach_inbox():
    ch = _parse_channel()
    clock = {"t": 100.0}
    ch._clock = lambda: clock["t"]
    ch._buf.extend(_frame_bytes({"kind": "hb", "seq": 5, "rid": "r0"}))
    ch._parse()
    assert not ch._inbox and ch.hb_seq == 5
    assert ch.last_heartbeat == 100.0
    clock["t"] = 200.0                 # a seq REGRESSION must not refresh
    ch._buf.extend(_frame_bytes({"kind": "hb", "seq": 3, "rid": "r0"}))
    ch._parse()
    assert ch.hb_seq == 5 and ch.last_heartbeat == 100.0


def test_frame_parser_rejects_oversized_length_prefix():
    ch = _parse_channel()
    ch._buf.extend(struct.pack(">I", (1 << 30) + 1))
    with pytest.raises(TransportError):
        ch._parse()


# ----------------------------------------------------------------------
# channel protocol over a real socketpair
# ----------------------------------------------------------------------
def _responder(sock, script):
    """Read request frames off the worker end; ``script(frame)`` returns
    the response dict to send (or None to stay silent)."""
    stream = sock.makefile("rb")

    def run():
        from deepspeed_tpu.inference.transport import recv_frame
        while True:
            try:
                frame = recv_frame(stream)
            except TransportError:
                return
            resp = script(frame)
            if resp is not None:
                try:
                    send_frame(sock, resp)
                except TransportError:
                    return
    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


def test_idempotent_retry_fresh_cid_same_ikey():
    """Two injected timeouts then a live attempt: the op retries under
    FRESH cids 0→1→2 while the idempotency key rides every attempt
    unchanged, the backoff schedule is the policy's, and exactly ONE
    frame ever reaches the worker."""
    a, b = socket.socketpair()
    seen, delays, retried = [], [], []
    try:
        ch = RpcChannel(
            a,
            wire=WireFaultInjector({"rpc_timeout": {"action": "timeout",
                                                    "times": 2}}),
            retry=RetryPolicy(max_retries=2, backoff_secs=0.01,
                              backoff_max_secs=0.05, jitter=0.0,
                              sleep_fn=delays.append))
        ch.on_retry = lambda op, att, d, el: retried.append((op, att, d))
        _responder(b, lambda f: (seen.append(f),
                                 {"kind": "resp", "cid": f["cid"],
                                  "ok": True})[1])
        resp = ch.call("bump", timeout=5.0, idempotent=True, ikey="k0")
        assert resp["ok"] is True
        assert ch.retries == 2
        assert [s["cid"] for s in seen] == [2]   # cids 0,1 never sent
        assert seen[0]["ikey"] == "k0"
        assert delays == [0.01, 0.02]            # base, then doubled
        assert [(op, att) for op, att, _ in retried] == \
            [("bump", 1), ("bump", 2)]
    finally:
        a.close()
        b.close()


def test_non_idempotent_call_never_retries():
    a, b = socket.socketpair()
    try:
        ch = RpcChannel(
            a,
            wire=WireFaultInjector({"rpc_timeout": {"action": "timeout",
                                                    "times": 5}}),
            retry=RetryPolicy(max_retries=3, backoff_secs=0.01,
                              sleep_fn=lambda s: None))
        with pytest.raises(RpcTimeout):
            ch.call("step", timeout=5.0)
        assert ch.retries == 0
    finally:
        a.close()
        b.close()


def test_timeout_desync_resync_one_byte_response():
    """THE regression: a reply trickling in one byte at a time crosses
    the call deadline — the call times out with a partial frame
    buffered.  The buffered parser must self-heal, the late reply must
    be discarded BY CALL ID, and the next call must succeed."""
    a, b = socket.socketpair()
    stale = []
    try:
        ch = RpcChannel(a)
        ch.on_stale = lambda op, kind: stale.append((op, kind))
        late = _frame_bytes({"kind": "resp", "cid": 0, "val": "late"})

        def drip_half():
            time.sleep(0.05)
            for i in range(len(late) // 2):   # one byte at a time...
                b.sendall(late[i:i + 1])      # ...stopping mid-frame

        t = threading.Thread(target=drip_half, daemon=True)
        t.start()
        with pytest.raises(RpcTimeout):
            ch.call("x", timeout=0.4)
        t.join()
        assert ch.desynced
        b.sendall(late[len(late) // 2:])      # the tail arrives late

        def answer_second(f):
            if f.get("cid") == 1:
                return {"kind": "resp", "cid": 1, "val": "fresh"}
            return None                       # ignore the stale request
        _responder(b, answer_second)
        resp = ch.call("y", timeout=5.0)
        assert resp["val"] == "fresh"         # never the cid-0 reply
        assert not ch.desynced
        assert ch.stale_drops == 1
        assert stale == [("y", "stale_resp")]
    finally:
        a.close()
        b.close()


def test_recv_dup_extra_copy_dropped_by_cid():
    a, b = socket.socketpair()
    try:
        ch = RpcChannel(
            a, wire=WireFaultInjector({"wire_recv": {"dup_at": [0]}}))
        _responder(b, lambda f: {"kind": "resp", "cid": f["cid"]})
        ch.call("p", timeout=5.0)             # delivered twice
        ch.call("q", timeout=5.0)             # extra copy is stale now
        assert ch.stale_drops == 1
    finally:
        a.close()
        b.close()


# ----------------------------------------------------------------------
# worker-side exactly-once dedup (cid cache + ikey replay)
# ----------------------------------------------------------------------
def _worker_pair():
    """A FleetWorker over a socketpair with one side-effecting test op
    patched in (the real ops need an engine; the dedup layer does not)."""
    a, b = socket.socketpair()
    worker = FleetWorker(b)
    calls = {"n": 0}

    def _op_bump(frame):
        calls["n"] += 1
        return {"n": calls["n"]}
    worker._op_bump = _op_bump
    t = threading.Thread(target=worker.serve, daemon=True)
    t.start()
    return a, b, worker, calls


def test_worker_duplicate_cid_resends_cached_response():
    a, b, worker, calls = _worker_pair()
    try:
        ch = RpcChannel(a)
        frame = {"op": "bump", "cid": 0}
        send_frame(a, frame)
        send_frame(a, frame)                  # exact duplicate delivery
        deadline = time.monotonic() + 5.0
        while len(ch._inbox) < 2 and time.monotonic() < deadline:
            ch.pump()
            time.sleep(0.005)
        first, second = ch._inbox.popleft(), ch._inbox.popleft()
        assert first == second                # resent verbatim
        assert first["n"] == 1
        assert calls["n"] == 1                # executed exactly once
        assert worker.dup_calls == 1
    finally:
        a.close()
        b.close()


def test_worker_ikey_replay_returns_recorded_outcome():
    """A retry under a fresh cid but the same ikey must replay the
    recorded outcome flagged ``dup`` — never re-execute the mutation."""
    a, b, worker, calls = _worker_pair()
    try:
        ch = RpcChannel(a)
        r1 = ch.call("bump", timeout=5.0, ikey="k1")
        assert r1["n"] == 1 and "dup" not in r1
        r2 = ch.call("bump", timeout=5.0, ikey="k1")   # fresh cid 1
        assert r2["n"] == 1 and r2["dup"] is True
        assert calls["n"] == 1
        assert worker.dup_calls == 1
        r3 = ch.call("bump", timeout=5.0, ikey="k2")   # new key executes
        assert r3["n"] == 2 and "dup" not in r3
        assert calls["n"] == 2
    finally:
        a.close()
        b.close()


def test_worker_cid_cache_is_bounded():
    a, b, worker, calls = _worker_pair()
    try:
        ch = RpcChannel(a)
        for _ in range(FleetWorker.MAX_CID_CACHE + 1):
            ch.call("bump", timeout=5.0)
        assert 0 not in worker._resp_by_cid   # oldest cid evicted
        assert len(worker._resp_by_cid) == FleetWorker.MAX_CID_CACHE
    finally:
        a.close()
        b.close()


# ----------------------------------------------------------------------
# CircuitBreaker state machine (fake clock)
# ----------------------------------------------------------------------
class _Tcfg:
    breaker_failures = 3
    breaker_open_s = 1.0
    breaker_open_max_s = 8.0
    breaker_flap_window_s = 30.0
    breaker_probes = 2
    breaker_probe_timeout_s = 5.0


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def test_breaker_opens_after_consecutive_timeouts_only():
    clock = _Clock()
    br = CircuitBreaker(_Tcfg(), clock)
    assert not br.record_failure() and not br.record_failure()
    br.record_success()                       # run broken → start over
    assert not br.record_failure() and not br.record_failure()
    assert br.record_failure()                # third consecutive trips
    assert br.open() == 1.0
    assert br.state == "open" and br.opens == 1


def test_breaker_halfopen_probe_cycle():
    clock = _Clock()
    br = CircuitBreaker(_Tcfg(), clock)
    for _ in range(3):
        br.record_failure()
    br.open()
    assert not br.probe_due()                 # cooldown still running
    clock.t += 1.0
    assert br.probe_due() and br.state == "half_open"
    br.close()
    assert br.state == "closed" and br.closes == 1
    assert br.consecutive == 0


def test_breaker_probe_failures_double_then_escalate():
    clock = _Clock()
    br = CircuitBreaker(_Tcfg(), clock)
    for _ in range(3):
        br.record_failure()
    br.open()
    clock.t += 1.0
    assert br.probe_due()
    assert not br.probe_failed()              # 1st failed probe: re-arm
    assert br.state == "open" and br.cooldown_s == 2.0
    clock.t += 2.0
    assert br.probe_due()
    assert br.probe_failed()                  # budget spent → escalate
    assert br.probe_failures == 2


def test_breaker_flap_window_doubles_cooldown_capped():
    clock = _Clock()
    br = CircuitBreaker(_Tcfg(), clock)
    assert br.open() == 1.0                   # first open: base cooldown
    br.close()
    clock.t += 0.5                            # re-open INSIDE the window
    assert br.open() == 2.0
    br.close()
    clock.t += 0.5
    for _ in range(5):                        # keep flapping → cap
        br.close()
        clock.t += 0.5
        br.open()
    assert br.cooldown_s == 8.0               # breaker_open_max_s
    br.close()
    clock.t += 100.0                          # settle PAST the window
    assert br.open() == 1.0                   # hysteresis resets


def test_breaker_disabled_when_failures_zero():
    cfg = _Tcfg()
    cfg.breaker_failures = 0
    br = CircuitBreaker(cfg, _Clock())
    assert not br.enabled
    assert not br.record_failure()            # never trips


# ----------------------------------------------------------------------
# vocabulary lockstep
# ----------------------------------------------------------------------
def test_wire_fault_sites_are_fault_sites_tail():
    """Chaos configs, docs, and the resilience injector share ONE site
    vocabulary: the wire sites are the frozen tail of FAULT_SITES, same
    names, same order."""
    from deepspeed_tpu.runtime.resilience import FAULT_SITES
    assert FAULT_SITES[-len(WIRE_FAULT_SITES):] == WIRE_FAULT_SITES


# ----------------------------------------------------------------------
# subprocess end-to-end (slow): exactly-once + breaker/liveness
# ----------------------------------------------------------------------
def _prompts(n=3):
    rng = np.random.default_rng(9)
    return {f"c{i}": rng.integers(0, 256, (8,)).tolist()
            for i in range(n)}


def _drive(router, settle=None, wall_s=120.0):
    deadline = time.monotonic() + wall_s
    for _ in range(2000):
        router.step()
        if not router._unresolved() and (settle is None or
                                         settle(router)):
            break
        assert time.monotonic() < deadline, "chaos run wall-clock bound"
    assert not router._unresolved(), "fleet did not converge"
    return (dict(router.finished), router.pop_terminated(),
            router.leak_report(), dict(router.stats))


def _reference(prompts):
    router = FleetRouter(tiny_engine_factory,
                         fleet={"replicas": 2, "health_interval": 1000})
    try:
        for rid, p in sorted(prompts.items()):
            router.submit(rid, p, max_new_tokens=6, temperature=0.7,
                          seed=11)
        finished, term, leaks, _ = _drive(router)
        assert not term and leaks == {}
        return finished
    finally:
        router.close()


@pytest.mark.slow
def test_e2e_dropped_admission_ack_is_exactly_once():
    """The first ``add_request`` response is dropped on the floor: the
    channel retries under the same ikey, the worker replays the recorded
    admission instead of double-admitting, and every output stays
    bit-identical to the no-fault reference — with zero kills."""
    prompts = _prompts()
    ref = _reference(prompts)
    router = FleetRouter(SPEC, fleet={
        "replicas": 2, "health_interval": 1000,
        "transport": {
            "mode": "subprocess", "heartbeat_interval_s": 0.2,
            "heartbeat_deadline_s": 60.0, "call_timeout_s": 8.0,
            "retry": {"max_retries": 2, "backoff_s": 0.02,
                      "backoff_max_s": 0.1},
            "chaos": {"seed": 0,
                      "wire_recv": {"drop_at": [0],
                                    "ops": ["add_request"]}}}})
    try:
        for rid, p in sorted(prompts.items()):
            router.submit(rid, p, max_new_tokens=6, temperature=0.7,
                          seed=11)
        finished, term, leaks, stats = _drive(router)
    finally:
        router.close()
    assert leaks == {} and not term
    assert finished == ref                    # bit-identical through chaos
    assert stats["retries"] >= 1
    assert stats["dup_calls_dropped"] >= 1    # the ikey replay, observed
    assert stats["workers_lost"] == 0 and stats["respawns"] == 0


@pytest.mark.slow
def test_e2e_breaker_fences_without_killing_one_incident(tmp_path):
    """Breaker/liveness composition: consecutive step timeouts on one
    replica trip its breaker (fenced, requests redispatched), the
    half-open probe closes it, heartbeat death NEVER fires for the
    fenced replica, and the whole episode books exactly ONE incident
    bundle (trigger ``breaker_open``) — not a second ``worker_lost``."""
    prompts = _prompts()
    ref = _reference(prompts)
    tel = Telemetry().configure(TelemetryConfig(
        {"enabled": True, "output_path": str(tmp_path),
         "job_name": "chaos_breaker",
         "incidents": {"enabled": True, "cooldown_s": 0.0}}), rank=0)
    router = FleetRouter(SPEC, fleet={
        "replicas": 2, "health_interval": 1000,
        "transport": {
            "mode": "subprocess", "heartbeat_interval_s": 0.2,
            "heartbeat_deadline_s": 60.0, "call_timeout_s": 30.0,
            "retry": {"max_retries": 0},
            "breaker_failures": 2, "breaker_open_s": 0.2,
            "breaker_probe_timeout_s": 5.0,
            "chaos": {"seed": 0,
                      "rpc_timeout": {"action": "timeout", "times": 2,
                                      "ops": ["step"],
                                      "replicas": ["r0"]}}}},
        telemetry=tel)
    try:
        for rid, p in sorted(prompts.items()):
            router.submit(rid, p, max_new_tokens=6, temperature=0.7,
                          seed=11)
        finished, term, leaks, stats = _drive(
            router, settle=lambda r: r.stats["breaker_closes"] >= 1)
        assert router.replicas["r0"].state == "healthy"   # probe healed
    finally:
        router.close()
        tel.close()
    assert leaks == {} and not term
    assert finished == ref
    assert stats["breaker_opens"] == 1 and stats["breaker_closes"] == 1
    assert stats["workers_lost"] == 0 and stats["respawns"] == 0

    events_path = os.path.join(str(tmp_path), "chaos_breaker",
                               "events.jsonl")
    checker = _load_checker()
    assert checker.validate_file(events_path) == []
    with open(events_path) as f:
        events = [json.loads(ln) for ln in f if ln.strip()]
    opens = [e for e in events if e.get("kind") == "fleet"
             and e.get("name") == "fleet/breaker_open"]
    closes = [e for e in events if e.get("kind") == "fleet"
              and e.get("name") == "fleet/breaker_close"]
    assert len(opens) == 1 and len(closes) == 1
    bundles = [e for e in events if e.get("kind") == "incident"
               and e.get("name") == "incident/open"]
    assert [b.get("trigger") for b in bundles] == ["breaker_open"]
