"""ZeRO-Inference weight-streaming tests.

Parity model: reference ZeRO-Inference (stage-3 param offload reused for
inference, docs 2022-09-10-zero-inference.md): weights on host/NVMe,
streamed per layer.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                              TransformerConfig)
from deepspeed_tpu.parallel import groups

B, S = 2, 16


def _model():
    cfg = TransformerConfig.tiny(hidden_size=64, n_heads=4)
    m = CausalTransformerLM(cfg)
    return m, m.init(jax.random.key(0))


def _ids():
    return np.random.default_rng(0).integers(0, 256, (B, S))


def test_cpu_streaming_matches_dense():
    model, params = _model()
    ref = deepspeed_tpu.init_inference(model=model, params=params,
                                       dtype="fp32")
    ids = _ids()
    ref_logits, _ = ref.forward(ids)
    ref_out = ref.generate(ids, max_new_tokens=6)

    groups.reset_mesh()
    eng = deepspeed_tpu.init_inference(
        model=model, params=params, dtype="fp32",
        zero={"offload_param": {"device": "cpu"}})
    assert eng._streaming
    # no stacked layer weights resident on device
    assert "layers" not in eng.params
    logits, caches = eng.forward(ids)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               rtol=1e-4, atol=1e-4)
    out = eng.generate(ids, max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref_out))


def test_nvme_streaming_generate(tmp_path):
    model, params = _model()
    ref = deepspeed_tpu.init_inference(model=model, params=params,
                                       dtype="fp32")
    ids = _ids()
    ref_out = ref.generate(ids, max_new_tokens=5)

    groups.reset_mesh()
    eng = deepspeed_tpu.init_inference(
        model=model, params=params, dtype="fp32",
        zero={"offload_param": {"device": "nvme",
                                "nvme_path": str(tmp_path)}})
    assert eng._tiered is not None
    import os
    swaps = os.listdir(eng._tiered.nvme_path)
    assert any(f.endswith(".bin") for f in swaps)  # weights on "NVMe"
    from deepspeed_tpu.runtime import resilience
    assert eng._tiered.validate()[0] == resilience.COMMITTED
    out = eng.generate(ids, max_new_tokens=5)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref_out))


def test_streaming_rejects_sampling():
    model, params = _model()
    eng = deepspeed_tpu.init_inference(
        model=model, params=params, dtype="fp32",
        zero={"offload_param": {"device": "cpu"}})
    with pytest.raises(AssertionError, match="greedy"):
        eng.generate(_ids(), max_new_tokens=2, temperature=0.7)


def test_int8_streaming_generate():
    """int8 weight streaming: the host store holds groupwise int8 +
    scales (half the per-layer H2D of bf16 — the streamed-inference
    bottleneck), dequantised inside the jitted layer step; greedy
    generation matches the fp32 dense engine."""
    model, params = _model()
    ref = deepspeed_tpu.init_inference(model=model, params=params,
                                       dtype="fp32")
    ids = _ids()
    ref_out = ref.generate(ids, max_new_tokens=6)

    groups.reset_mesh()
    eng = deepspeed_tpu.init_inference(
        model=model, params=params, dtype="fp32",
        quant={"enabled": True, "num_bits": 8},
        zero={"offload_param": {"device": "cpu"}})
    assert eng._streaming and eng._quantized
    # matmul weights in the host store are int8 dicts; norms stay fp
    l0 = eng._host_layers[0]
    assert l0["wq"]["qv"].dtype == np.int8
    assert "qs" in l0["wq"] and not isinstance(l0["attn_norm"], dict)
    out = eng.generate(ids, max_new_tokens=6)
    agree = np.mean(np.asarray(out)[:, -6:] == np.asarray(ref_out)[:, -6:])
    assert agree >= 0.5, agree   # int8 may flip near-ties, not the bulk
    groups.reset_mesh()


def test_int8_streaming_nvme_generate(tmp_path):
    """int8 + NVMe — the hole the tiered store closes: groupwise int8
    weights live on NVMe with their per-group scale sidecars as separate
    manifest-listed files, stream per layer, and greedy generation stays
    in family with the fp32 dense engine."""
    import os
    model, params = _model()
    ref = deepspeed_tpu.init_inference(model=model, params=params,
                                       dtype="fp32")
    ids = _ids()
    ref_out = ref.generate(ids, max_new_tokens=6)

    groups.reset_mesh()
    eng = deepspeed_tpu.init_inference(
        model=model, params=params, dtype="fp32",
        quant={"enabled": True, "num_bits": 8},
        zero={"offload_param": {"device": "nvme",
                                "nvme_path": str(tmp_path)}})
    assert eng._streaming and eng._quantized and eng._tiered is not None
    swaps = os.listdir(eng._tiered.nvme_path)
    # quantized leaves on disk as qv/qs/qz triples (scale sidecars)
    assert any(".wq.qv.bin" in f for f in swaps), swaps
    assert any(".wq.qs.bin" in f for f in swaps), swaps
    from deepspeed_tpu.runtime import resilience
    status, manifest = eng._tiered.validate()
    assert status == resilience.COMMITTED
    listed = {f["path"] for f in manifest["files"]}
    assert any(".wq.qs.bin" in p for p in listed)  # sidecar in manifest
    out = eng.generate(ids, max_new_tokens=6)
    agree = np.mean(np.asarray(out)[:, -6:] == np.asarray(ref_out)[:, -6:])
    assert agree >= 0.5, agree   # int8 may flip near-ties, not the bulk
    groups.reset_mesh()
