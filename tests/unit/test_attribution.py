"""Time-attribution plane tests (monitor/attribution.py): the interval
algebra and its precedence decomposition, the telemetry-tapped
AttributionPlane (frozen ``step/attr/*`` gauges, ``/attribution``
endpoint), the wire-propagable RequestAttributor, and the end-to-end
FakeClock invariant this plane exists to guarantee — every traced
serving request's stage attributions sum to its traced e2e latency,
including requests that cross a prefill -> decode migration with their
TraceContext round-tripped through a serialized PrefillHandoff under
injected migration faults."""

import importlib.util
import json
import os
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from deepspeed_tpu.inference.fleet import FleetRouter
from deepspeed_tpu.inference.serving import ServingEngine
from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                              TransformerConfig)
from deepspeed_tpu.monitor.attribution import (ATTR_STAGES,
                                               STEP_ATTR_GAUGES,
                                               RequestAttributor,
                                               TraceContext,
                                               decompose_step,
                                               merge_intervals,
                                               overlap_length,
                                               request_stages,
                                               total_length)
from deepspeed_tpu.monitor.telemetry import Telemetry
from deepspeed_tpu.runtime.config import TelemetryConfig
from deepspeed_tpu.runtime.resilience import FaultInjector

# sum of the rounded per-stage values vs the rounded e2e: each of the
# five stages contributes at most 0.5e-3 ms of rounding — 0.01 ms is
# an order of magnitude of headroom, zero behavioral slack
SUM_TOL_MS = 0.01


def _load_script(name):
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    path = os.path.join(repo, "scripts", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class Tick:
    """Deterministic fake clock: every read advances 1 ms, so every
    stage of every request gets a nonzero, reproducible duration."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        self.t += 0.001
        return self.t


# ----------------------------------------------------------------------
# interval algebra + precedence decomposition
# ----------------------------------------------------------------------
def test_interval_algebra():
    assert merge_intervals([(3, 4), (1, 2), (1.5, 3.5)]) == [(1.0, 4.0)]
    assert merge_intervals([(1, 1), (2, 1)]) == []   # degenerate dropped
    assert total_length([(0, 1), (0.5, 2), (3, 4)]) == pytest.approx(3.0)
    assert overlap_length([(0, 10)], [(2, 3), (5, 7)]) == \
        pytest.approx(3.0)
    assert overlap_length([(0, 1)], [(2, 3)]) == 0.0


def test_decompose_components_sum_to_step():
    rec = decompose_step(0.0, 0.1,
                         compute=[(0.010, 0.040), (0.045, 0.085)],
                         comm=[(0.030, 0.060)],
                         input_wait=[(0.000, 0.010)])
    parts = (rec["compute_ms"] + rec["exposed_comm_ms"] +
             rec["input_wait_ms"] + rec["host_sync_ms"] +
             rec["compile_ms"])
    assert parts == pytest.approx(rec["step_ms"], abs=SUM_TOL_MS)
    # the collective overlaps 25 of its 30 ms with compute: only the
    # 5 ms inter-span gap is exposed
    assert rec["exposed_comm_ms"] == pytest.approx(5.0, abs=1e-6)
    assert rec["exposed_comm_frac"] == pytest.approx(0.05, rel=0.02)


def test_decompose_compile_precedence_no_double_count():
    """A compile nested inside the forward span (the cache-miss reality)
    counts once as compile, not again as compute."""
    rec = decompose_step(0.0, 0.1,
                         compute=[(0.010, 0.090)],
                         compiles=[(0.020, 0.050)])
    assert rec["compile_ms"] == pytest.approx(30.0)
    assert rec["compute_ms"] == pytest.approx(50.0)
    assert rec["host_sync_ms"] == pytest.approx(20.0)


def test_decompose_exposed_frac_matches_analytic_workload():
    """The acceptance construction: per-rank comm skew shifts which
    compute span the collective overlaps but never its total, so the
    exposed fraction is exactly 0.05 at every skew (within 2%)."""
    for skew_ms in range(4):
        k = skew_ms / 1000.0
        rec = decompose_step(0.0, 0.1,
                             compute=[(0.010, 0.040), (0.045, 0.085)],
                             comm=[(0.030 + k, 0.060 + k)],
                             input_wait=[(0.000, 0.010)])
        assert rec["exposed_comm_frac"] == pytest.approx(0.05, rel=0.02)


# ----------------------------------------------------------------------
# the telemetry-tapped plane
# ----------------------------------------------------------------------
def test_plane_decomposes_steps_and_serves_endpoint(tmp_path):
    tel = Telemetry().configure(TelemetryConfig(
        {"enabled": True, "output_path": str(tmp_path),
         "job_name": "attr", "export": {"enabled": True, "port": 0},
         "attribution": {"enabled": True, "history": 8}}), rank=0)
    try:
        plane = tel.attribution
        assert plane is not None
        import time
        base = time.time()
        for s in range(3):
            w0 = base + s
            plane.record({"ts": w0 + 0.040, "kind": "span",
                          "name": "engine/forward", "dur_ms": 30.0})
            plane.record({"ts": w0 + 0.085, "kind": "span",
                          "name": "engine/backward", "dur_ms": 40.0})
            plane.record({"ts": w0 + 0.060, "kind": "comm",
                          "name": "all_reduce", "dur_ms": 30.0})
            plane.record({"ts": w0 + 0.100, "kind": "heartbeat",
                          "name": "engine/step", "step": s,
                          "step_ms": 100.0})
        snap = plane.snapshot()
        assert snap["steps_attributed"] == 3
        for rec in snap["steps"]:
            parts = sum(rec[k] for k in
                        ("compute_ms", "exposed_comm_ms",
                         "input_wait_ms", "host_sync_ms", "compile_ms"))
            assert parts == pytest.approx(rec["step_ms"],
                                          abs=SUM_TOL_MS)
        host, port = tel.exporter.address
        scraped = json.loads(urllib.request.urlopen(
            f"http://{host}:{port}/attribution", timeout=5).read())
        assert scraped["steps_attributed"] == 3
        assert scraped["last"]["exposed_comm_frac"] == \
            pytest.approx(0.05, rel=0.02)
    finally:
        tel.close()
    checker = _load_script("check_telemetry_schema")
    path = os.path.join(str(tmp_path), "attr", "events.jsonl")
    assert checker.validate_file(path) == []
    with open(path) as f:
        events = [json.loads(ln) for ln in f if ln.strip()]
    names = {e["name"] for e in events if e["kind"] == "gauge"
             and e["name"].startswith("step/attr/")}
    assert names == set(STEP_ATTR_GAUGES)


def test_plane_off_means_attribute_is_none(tmp_path):
    tel = Telemetry().configure(TelemetryConfig(
        {"enabled": True, "output_path": str(tmp_path),
         "job_name": "noattr"}), rank=0)
    try:
        assert tel.attribution is None
    finally:
        tel.close()


def test_first_beat_only_arms_the_window(tmp_path):
    tel = Telemetry().configure(TelemetryConfig(
        {"enabled": True, "output_path": str(tmp_path),
         "job_name": "beat", "attribution": {"enabled": True}}), rank=0)
    try:
        plane = tel.attribution
        plane.beat(0, now=10.0)
        assert plane.steps_attributed == 0
        plane.beat(1, now=10.1)
        assert plane.steps_attributed == 1
        assert plane.history[-1]["step_ms"] == pytest.approx(100.0)
    finally:
        tel.close()


# ----------------------------------------------------------------------
# the serving half: RequestAttributor + TraceContext wire round-trip
# ----------------------------------------------------------------------
def test_trace_context_wire_is_plain_primitives():
    ctx = TraceContext(req_id="r1", t_admit=1.0, t_prefill_start=1.5,
                       prefill_active_ms=12.5, chunks=3)
    wire = ctx.to_wire()
    assert json.loads(json.dumps(wire)) == wire   # wire-ready
    back = TraceContext.from_wire(wire)
    assert back.migrated                          # crossing marks it
    assert back.t_admit == 1.0 and back.chunks == 3


def test_request_stages_sum_exactly():
    ctx = TraceContext(req_id="r", t_admit=0.0, t_prefill_start=0.040,
                       t_first_token=0.100, t_handoff=0.080,
                       t_import=0.095, prefill_active_ms=25.0, chunks=2,
                       migrated=True)
    st = request_stages(ctx, 0.200)
    assert st["queue_ms"] == pytest.approx(40.0)
    assert st["prefill_ms"] == pytest.approx(25.0)
    assert st["migrate_ms"] == pytest.approx(15.0)
    assert st["decode_ms"] == pytest.approx(85.0)
    assert sum(st[f"{s}_ms"] for s in ATTR_STAGES) == \
        pytest.approx(st["e2e_ms"], abs=1e-9)


def test_attributor_migration_roundtrip_fake_clock():
    clock = Tick()
    src = RequestAttributor(clock=clock)
    dst = RequestAttributor(clock=clock)
    src.admit("m1")
    src.prefill_start("m1")
    src.chunk("m1", 0.4)
    src.first_token("m1")          # source-side TTFT
    wire = src.capture_handoff("m1")
    src_attrs = src.finalize("m1", "finish")
    dst.import_ctx("m1", json.loads(json.dumps(wire)))
    dst.first_token("m1")          # later decode-side token: must LOSE
    attrs = dst.finalize("m1", "finish")
    assert attrs["migrated"] == 1 and src_attrs["migrated"] == 0
    for a in (src_attrs, attrs):
        assert sum(a[f"{s}_ms"] for s in ATTR_STAGES) == \
            pytest.approx(a["e2e_ms"], abs=SUM_TOL_MS)
    # first-wins: the source's first-token timestamp survived the wire,
    # so decode stage spans from THAT stamp, not the decode-side re-stamp
    assert attrs["path"].startswith("queue>")
    assert "migrate" in attrs["path"]
    assert dst.finalize("unknown", "finish") is None


def test_attributor_discard_and_bad_wire():
    att = RequestAttributor(clock=Tick())
    att.import_ctx("x", None)          # legacy handoff without ctx
    assert att.finalize("x", "finish")["migrated"] == 0
    att.admit("y")
    att.discard("y")
    assert att.finalize("y", "evict") is None


# ----------------------------------------------------------------------
# end to end: FakeClock fleet with injected migration faults
# ----------------------------------------------------------------------
@pytest.fixture(scope="module")
def tiny():
    cfg = TransformerConfig.tiny(hidden_size=64, n_heads=4, n_kv_heads=2)
    model = CausalTransformerLM(cfg)
    params = model.init(jax.random.key(0))
    return cfg, model, params


def test_fleet_attr_events_sum_to_e2e_under_faults(tiny, tmp_path):
    """Every traced request in a disaggregated fleet run — with the
    shared FakeClock and transient migration faults injected — carries
    ``serve/request/attr`` events whose stage sum equals the traced
    ``e2e_ms`` within tolerance, with the migrated leg's context
    round-tripped through the serialized PrefillHandoff."""
    cfg, model, params = tiny
    clock = Tick()
    tel = Telemetry().configure(TelemetryConfig(
        {"enabled": True, "output_path": str(tmp_path),
         "job_name": "attr_fleet",
         "attribution": {"enabled": True}}), rank=0)

    def factory(replica_id, epoch):
        return ServingEngine(model, params, max_batch=4, page_size=8,
                             max_seq=128, dtype=jnp.float32,
                             replica_epoch=epoch, clock=clock,
                             telemetry=tel)

    try:
        fleet = FleetRouter(
            factory,
            fleet={"roles": {"enabled": True, "prefill_replicas": 1,
                             "decode_replicas": 2}},
            telemetry=tel, clock=clock)
        fleet.injector = FaultInjector(
            {"page_migrate": {"fail_times": 2},
             "migrate_commit": {"fail_times": 1}})
        import numpy as np
        rng = np.random.default_rng(0)
        for i in range(6):
            fleet.submit(f"q{i}",
                         rng.integers(0, cfg.vocab_size, (12,)).tolist(),
                         max_new_tokens=4, temperature=0.7, seed=11)
        done = fleet.join()
        assert len(done) == 6
    finally:
        tel.close()

    path = os.path.join(str(tmp_path), "attr_fleet", "events.jsonl")
    checker = _load_script("check_telemetry_schema")
    assert checker.validate_file(path) == []
    with open(path) as f:
        events = [json.loads(ln) for ln in f if ln.strip()]
    terminals, attrs_by_req, e2e_by_key = {}, {}, {}
    for ev in events:
        if ev.get("kind") != "serve":
            continue
        name, a = ev["name"], ev.get("attrs") or {}
        if name == "serve/request/attr":
            attrs_by_req.setdefault(a["req_id"], []).append(a)
        elif name.startswith("serve/request/") and \
                name.rsplit("/", 1)[1] in ("finish", "shed", "deadline",
                                           "evict"):
            terminals.setdefault(a["req_id"], []).append(a)
    assert set(terminals) == {f"q{i}" for i in range(6)}
    for rid, terms in terminals.items():
        paths = attrs_by_req.get(rid, [])
        # one attr event adjacent to EVERY terminal (the migrated
        # requests close twice: source leg at handoff, full path at
        # finish)
        assert len(paths) == len(terms), rid
        for a in paths:
            stage_sum = sum(a[f"{s}_ms"] for s in ATTR_STAGES)
            assert stage_sum == pytest.approx(a["e2e_ms"],
                                              abs=SUM_TOL_MS), rid
        # the decode-side leg of each migrated request crossed the wire
        migrated = [a for a in paths if a["migrated"] == 1]
        for a in migrated:
            assert a["migrate_ms"] > 0, rid
            assert "migrate" in a["path"], rid
    # the injected faults did not cost any request its attribution, and
    # migration did happen (prefill -> decode handoffs with trace_ctx)
    assert any(a["migrated"] == 1
               for paths in attrs_by_req.values() for a in paths)
    # non-migrated attr events agree exactly with a traced terminal e2e
    # (finalize closes on the SAME clock value the tracer stamped); the
    # migrated full-path leg spans the ORIGINAL admission, so it must
    # cover at least its decode-side tracer's own leg
    for rid, terms in terminals.items():
        term_e2es = [t["e2e_ms"] for t in terms
                     if t.get("e2e_ms") is not None]
        for a in attrs_by_req[rid]:
            if a["migrated"]:
                assert a["e2e_ms"] >= max(term_e2es) - SUM_TOL_MS, rid
            else:
                assert any(a["e2e_ms"] == pytest.approx(t)
                           for t in term_e2es), rid


# ----------------------------------------------------------------------
# downstream surfaces: trace export flow arrows, incident correlation
# ----------------------------------------------------------------------
def test_trace_export_renders_attr_critical_path(tmp_path):
    exporter = _load_script("ds_trace_export")
    stream = tmp_path / "events.jsonl"
    rows = [
        {"ts": 100.0, "kind": "serve", "name": "serve/request/admitted",
         "attrs": {"req_id": "r1"}},
        {"ts": 100.2, "kind": "serve", "name": "serve/request/finish",
         "attrs": {"req_id": "r1", "n_generated": 4}},
        {"ts": 100.2, "kind": "serve", "name": "serve/request/attr",
         "attrs": {"req_id": "r1", "terminal": "finish", "migrated": 1,
                   "chunks": 2, "path": "queue>prefill>migrate>decode",
                   "queue_ms": 40.0, "prefill_ms": 25.0,
                   "migrate_ms": 15.0, "gap_ms": 35.0,
                   "decode_ms": 85.0, "e2e_ms": 200.0}},
    ]
    with open(stream, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    obj = exporter.convert(exporter.load_events(str(stream)))
    assert exporter.validate_trace(obj) == []
    evs = obj["traceEvents"]
    slices = [e for e in evs if e.get("cat") == "attr"]
    assert [e["name"] for e in slices] == \
        ["attr/queue", "attr/prefill", "attr/migrate", "attr/gap",
         "attr/decode"]
    # contiguous: each slice starts where the previous ended
    for prev, cur in zip(slices, slices[1:]):
        assert cur["ts"] == pytest.approx(prev["ts"] + prev["dur"],
                                          abs=0.2)
    flows = [e for e in evs if e.get("cat") == "attr-flow"]
    assert [e["ph"] for e in flows] == ["s", "t", "t", "t", "f"]
    assert all(e["id"] == "attr:r1" for e in flows)


def test_correlate_links_attribution_to_requests():
    from deepspeed_tpu.monitor.incidents import correlate
    events = [
        {"ts": 10.0, "kind": "serve", "name": "serve/request/deadline",
         "attrs": {"req_id": "r1", "e2e_ms": 55.0, "slo": "miss"}},
        {"ts": 10.0, "kind": "serve", "name": "serve/request/attr",
         "attrs": {"req_id": "r1", "terminal": "deadline",
                   "queue_ms": 40.0, "prefill_ms": 10.0,
                   "migrate_ms": 0.0, "gap_ms": 2.0, "decode_ms": 3.0,
                   "e2e_ms": 55.0, "migrated": 0, "chunks": 1,
                   "path": "queue>prefill>decode"}},
        {"ts": 10.1, "kind": "compile", "name": "compile/miss",
         "site": "serve_step", "dur_ms": 30.0},
    ]
    out = correlate(events)
    assert out["links"], "expected a compile<->miss correlation link"
    link = out["links"][0]
    assert link["req_id"] == "r1"
    assert link["attribution"]["queue_ms"] == 40.0
    # the attr event must NOT read as a bogus extra terminal
    window_reqs = [r for w in out["windows"] for r in w["requests"]]
    assert [r["event"] for r in window_reqs] == ["deadline"]
