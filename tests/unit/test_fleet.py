"""Fleet serving resilience tests (inference/fleet.py): prefix-affinity
routing, replica supervision, zero-loss failover under injected replica
kills, dispatch atomicity, redispatch budgets, autoscaling, drain
accounting, and the ``GET /fleet`` export surface.

Oracle discipline (inherited from the serving hardening tests): a
request's output depends only on (prompt, sampling params, seed) — never
on which replica, batch, or dispatch attempt served it — so failover may
RE-SERVE a request, never perturb one.  The acceptance scenario runs the
same shared-prefix workload with and without an injected mid-flight
``replica_kill`` and demands bit-identical finished outputs."""

import importlib.util
import json
import os
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.elasticity import ReplicaAutoscaler
from deepspeed_tpu.inference.fleet import (FLEET_EVENTS, FleetConfig,
                                           FleetRouter,
                                           SHED_REDISPATCH_BUDGET)
from deepspeed_tpu.inference.robustness import (REJECT_DRAINING,
                                                REJECT_DUPLICATE,
                                                RequestRejected,
                                                RequestTracer,
                                                ServingRobustnessConfig)
from deepspeed_tpu.inference.serving import ServingEngine
from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                              TransformerConfig)
from deepspeed_tpu.monitor.telemetry import Telemetry
from deepspeed_tpu.runtime.config import TelemetryConfig
from deepspeed_tpu.runtime.resilience import FAULT_SITES, FaultInjector


@pytest.fixture(scope="module")
def tiny():
    cfg = TransformerConfig.tiny(hidden_size=64, n_heads=4, n_kv_heads=2)
    model = CausalTransformerLM(cfg)
    params = model.init(jax.random.key(0))
    return cfg, model, params


def _factory(model, params, **overrides):
    """An ``engine_factory`` for FleetRouter: identical engines (required
    for bit-identical redispatch), prefix cache on, epoch plumbed."""
    def build(replica_id, epoch):
        kw = dict(max_batch=4, page_size=8, max_seq=128,
                  dtype=jnp.float32, replica_epoch=epoch,
                  serving={"prefix_cache": {"enabled": True}})
        kw.update(overrides)
        return ServingEngine(model, params, **kw)
    return build


def _family_prompts(cfg, n_families=6, per_family=2, prefix_len=24,
                    suffix_len=4, seed=0):
    """``n_families`` shared 24-token prefixes (3 KV pages at page_size=8)
    with distinct short suffixes — the prefix-cache-friendly workload."""
    rng = np.random.default_rng(seed)
    fams = [rng.integers(0, cfg.vocab_size, (prefix_len,)).tolist()
            for _ in range(n_families)]
    prompts = {}
    for fi, fam in enumerate(fams):
        for j in range(per_family):
            suffix = rng.integers(0, cfg.vocab_size, (suffix_len,)).tolist()
            prompts[f"f{fi}q{j}"] = fam + suffix
    return prompts


def _assert_zero_loss(fleet, n_submitted):
    """Every submitted id reaches exactly one terminal and nothing leaks."""
    st = fleet.stats
    assert st["submitted"] == n_submitted
    assert st["finished"] + st["terminated"] == n_submitted
    done = set(fleet.finished)
    term = {rid for rid, fr in fleet.requests.items()
            if fr.state == "terminated"}
    assert done | term == set(fleet.requests)
    assert not (done & term)
    assert fleet.leak_report() == {}


# ----------------------------------------------------------------------
# config + frozen vocabularies
# ----------------------------------------------------------------------
def test_fleet_config_validation():
    for bad in ({"replicas": 0}, {"health_interval": 0},
                {"min_replicas": 2, "max_replicas": 1},
                {"replicas": 5, "max_replicas": 4},
                {"redispatch_max": -1}, {"free_page_low_frac": 1.5}):
        with pytest.raises(ValueError):
            FleetConfig(bad)
    with pytest.raises(ValueError):
        FleetConfig({"bogus_knob": 1}, strict=True)
    # the serving config nests and promotes the fleet block
    cfg = ServingRobustnessConfig({"fleet": {"replicas": 3,
                                             "redispatch_max": 5}})
    assert isinstance(cfg.fleet, FleetConfig)
    assert cfg.fleet.replicas == 3 and cfg.fleet.redispatch_max == 5


def test_fleet_fault_sites_frozen():
    assert "replica_kill" in FAULT_SITES
    assert "route_dispatch" in FAULT_SITES
    assert len(FLEET_EVENTS) == len(set(FLEET_EVENTS))
    assert all(name.startswith("fleet/") for name in FLEET_EVENTS)


# ----------------------------------------------------------------------
# tracer epoch namespacing (the respawn double-admit fix)
# ----------------------------------------------------------------------
def test_request_tracer_epoch_namespacing():
    t0 = RequestTracer(clock=lambda: 0.0, epoch="r1g0")
    t1 = RequestTracer(clock=lambda: 0.0, epoch="r1g1")
    # the same redispatched id admits cleanly under each generation
    t0.admit("q", now=0.0)
    t1.admit("q", now=0.0)
    assert t0.errors == [] and t1.errors == []
    # audit maps live ids through the namespace before comparing
    assert t0.audit(["q"]) == {}
    t1.terminal("q", "shed", reason="fault")
    assert t1.audit([]) == {}
    # a genuine double admit WITHIN one epoch still trips, and the error
    # keeps the epoch-qualified id so the generation stays visible
    t0.admit("q", now=0.0)
    assert any("r1g0:q" in e for e in t0.errors)


# ----------------------------------------------------------------------
# prefix-affinity routing
# ----------------------------------------------------------------------
def test_prefix_affinity_routing(tiny):
    cfg, model, params = tiny
    prompts = _family_prompts(cfg, n_families=6)

    def owners():
        fleet = FleetRouter(_factory(model, params),
                            fleet={"replicas": 3, "max_replicas": 3})
        for rid, p in sorted(prompts.items()):
            fleet.submit(rid, p, max_new_tokens=2)
        return {rid: fleet.requests[rid].replica_id for rid in prompts}

    a, b = owners(), owners()
    # routing is a pure function of (prompt prefix, healthy ring)
    assert a == b
    # same family -> same routing key -> same replica
    for fi in range(6):
        assert a[f"f{fi}q0"] == a[f"f{fi}q1"]
    # rendezvous hashing actually spreads families across the ring
    assert len(set(a.values())) >= 2


def test_fleet_basic_serve_matches_single_engine(tiny):
    cfg, model, params = tiny
    prompts = _family_prompts(cfg, n_families=3)
    fleet = FleetRouter(_factory(model, params),
                        fleet={"replicas": 2, "max_replicas": 2})
    for rid, p in sorted(prompts.items()):
        fleet.submit(rid, p, max_new_tokens=4)
    done = fleet.join()
    _assert_zero_loss(fleet, len(prompts))

    single = ServingEngine(model, params, max_batch=4, page_size=8,
                           max_seq=128, dtype=jnp.float32)
    for rid, p in sorted(prompts.items()):
        single.add_request(rid, p, max_new_tokens=4)
    alone = {}
    while single.queue or single.n_active:
        alone.update(single.step())
    for rid in prompts:
        assert done[rid] == alone[rid], rid

    with pytest.raises(RequestRejected) as ei:
        fleet.submit("f0q0", prompts["f0q0"], max_new_tokens=2)
    assert ei.value.reason == REJECT_DUPLICATE


# ----------------------------------------------------------------------
# the acceptance scenario: injected replica kill mid-flight
# ----------------------------------------------------------------------
def test_acceptance_replica_kill_zero_loss_bit_identical(tiny):
    cfg, model, params = tiny
    prompts = _family_prompts(cfg, n_families=6)
    seeds = sorted(r for r in prompts if r.endswith("q0"))
    rest = sorted(r for r in prompts if not r.endswith("q0"))

    def run(inject_kill):
        fleet = FleetRouter(_factory(model, params),
                            fleet={"replicas": 3, "max_replicas": 4,
                                   "health_interval": 2,
                                   "redispatch_max": 3})
        # phase 1: seed each family's prefix into its affinity replica
        for rid in seeds:
            fleet.submit(rid, prompts[rid], max_new_tokens=4)
        fleet.join(max_steps=400)
        # phase 2: the shared-prefix followers, killed mid-flight
        for rid in rest:
            fleet.submit(rid, prompts[rid], max_new_tokens=4)
        if inject_kill:
            # aim the injector at whichever replica owns the most
            # in-flight work: the supervision sweep consults the
            # replica_kill site once per healthy replica in ring order,
            # so fail_at=[index of the victim] kills it mid-flight
            owned = {}
            for fr in fleet.requests.values():
                if fr.state == "dispatched":
                    owned[fr.replica_id] = owned.get(fr.replica_id, 0) + 1
            victim = max(sorted(owned), key=lambda r: owned[r])
            order = list(fleet.replicas)
            fleet.injector = FaultInjector(
                {"replica_kill": {"fail_at": [order.index(victim)],
                                  "msg": "injected chaos kill"}})
            assert owned[victim] >= 1
        fleet.join(max_steps=800)
        return fleet

    clean = run(False)
    chaos = run(True)

    # the kill fired mid-flight, work was re-homed, the slot respawned
    assert chaos.stats["kills"] == 1
    assert chaos.stats["redispatches"] >= 1
    assert chaos.stats["respawns"] == 1
    assert chaos.injector.calls("replica_kill") >= 1

    # zero lost requests under chaos: every id reaches exactly one typed
    # terminal, and generous budgets mean they all actually finish
    for fleet in (clean, chaos):
        _assert_zero_loss(fleet, len(prompts))
    assert chaos.stats["terminated"] == 0

    # bit-identity: surviving AND redispatched outputs match the
    # no-fault run token for token
    assert chaos.finished == clean.finished

    # per-replica prefix hit rates stay at single-engine levels: replay
    # the same seed-then-followers workload on one engine as the oracle
    single = ServingEngine(model, params, max_batch=4, page_size=8,
                           max_seq=128, dtype=jnp.float32,
                           serving={"prefix_cache": {"enabled": True}})
    for batch in (seeds, rest):
        for rid in batch:
            single.add_request(rid, prompts[rid], max_new_tokens=4)
        while single.queue or single.n_active:
            single.step()
    single_rate = single.prefix_cache.snapshot()["hit_rate"]
    assert single_rate > 0.3
    rates = [r["prefix_hit_rate"]
             for r in clean.health()["replicas"].values()]
    assert rates and min(rates) >= single_rate - 0.05


# ----------------------------------------------------------------------
# dispatch atomicity (the page_alloc idiom at the route_dispatch site)
# ----------------------------------------------------------------------
def test_route_dispatch_fault_is_atomic(tiny):
    cfg, model, params = tiny
    prompts = _family_prompts(cfg, n_families=1, per_family=1)
    (rid, prompt), = prompts.items()
    fleet = FleetRouter(
        _factory(model, params),
        fleet={"replicas": 2, "max_replicas": 2},
        injector=FaultInjector({"route_dispatch": {"fail_times": 2,
                                                   "msg": "route chaos"}}))
    fleet.submit(rid, prompt, max_new_tokens=4)
    # the injected fault fired BEFORE any routing-table or engine
    # mutation: nothing half-registered, the request is simply pending
    assert fleet.stats["dispatch_faults"] == 1
    fr = fleet.requests[rid]
    assert fr.state == "pending" and fr.replica_id is None
    assert fr.dispatches == 0
    for rep in fleet.replicas.values():
        assert len(rep.engine.queue) == 0 and rep.engine.n_active == 0
    # retries burn the remaining fault then place and finish the request
    done = fleet.join(max_steps=200)
    assert set(done) == {rid}
    assert fleet.stats["dispatch_faults"] == 2
    assert fleet.injector.calls("route_dispatch") >= 3
    _assert_zero_loss(fleet, 1)


# ----------------------------------------------------------------------
# redispatch budget: a bouncing request terminates typed, never silently
# ----------------------------------------------------------------------
def test_redispatch_budget_exhaustion_is_typed(tiny):
    cfg, model, params = tiny
    prompts = _family_prompts(cfg, n_families=1, per_family=1)
    (rid, prompt), = prompts.items()
    fleet = FleetRouter(_factory(model, params),
                        fleet={"replicas": 1, "max_replicas": 1,
                               "redispatch_max": 0,
                               "health_interval": 1})
    fleet.submit(rid, prompt, max_new_tokens=8)
    assert fleet.requests[rid].state == "dispatched"
    fleet.kill_replica(next(iter(fleet.replicas)), detail="chaos drill")
    # budget 0: the kill's requeue immediately types the request out
    term = fleet.pop_terminated()
    assert set(term) == {rid}
    assert term[rid].status == "shed"
    assert term[rid].reason == SHED_REDISPATCH_BUDGET
    fleet.step()                      # supervision respawns the ring slot
    assert len(fleet.replicas) == 1
    assert next(iter(fleet.replicas.values())).epoch == "r0g1"
    _assert_zero_loss(fleet, 1)
    assert fleet.stats["terminated"] == 1


# ----------------------------------------------------------------------
# autoscaler: pure decisions, then wired through the fleet
# ----------------------------------------------------------------------
def test_replica_autoscaler_decisions():
    a = ReplicaAutoscaler(min_replicas=1, max_replicas=3,
                          scale_up_queue_per_replica=4,
                          scale_down_queue_per_replica=1,
                          cooldown_sweeps=2)
    assert a.decide(1, queue_depth=8) == 2        # queue pressure
    assert a.decide(2, queue_depth=9) == 2        # cooldown holds
    assert a.decide(2, queue_depth=9) == 2        # still cooling
    assert a.decide(2, queue_depth=9) == 3        # cooldown over
    b = ReplicaAutoscaler(min_replicas=1, max_replicas=2,
                          cooldown_sweeps=0)
    assert b.decide(1, shed_delta=1) == 2         # shed pressure
    c = ReplicaAutoscaler(min_replicas=1, max_replicas=2,
                          cooldown_sweeps=0, free_page_low_frac=0.2)
    assert c.decide(1, free_page_frac=0.1) == 2   # page pressure
    d = ReplicaAutoscaler(min_replicas=1, max_replicas=3,
                          cooldown_sweeps=0, scale_down_queue_per_replica=1)
    assert d.decide(3, queue_depth=0) == 2        # idle drains one at a time
    assert d.decide(1, queue_depth=0) == 1        # never below the floor
    assert d.scale_downs >= 1
    with pytest.raises(ValueError):
        ReplicaAutoscaler(min_replicas=0)
    with pytest.raises(ValueError):
        ReplicaAutoscaler(min_replicas=4, max_replicas=2)


def test_fleet_autoscales_up_under_pressure(tiny):
    cfg, model, params = tiny
    prompts = _family_prompts(cfg, n_families=5, per_family=2)
    fleet = FleetRouter(_factory(model, params),
                        fleet={"replicas": 1, "min_replicas": 1,
                               "max_replicas": 3, "health_interval": 1,
                               "autoscale": True,
                               "scale_up_queue_per_replica": 2,
                               "cooldown_sweeps": 0})
    for rid, p in sorted(prompts.items()):
        fleet.submit(rid, p, max_new_tokens=4)
    for _ in range(4):
        fleet.step()
    assert fleet.stats["scale_ups"] >= 1
    assert len(fleet.replicas) >= 2
    fleet.join()
    _assert_zero_loss(fleet, len(prompts))


# ----------------------------------------------------------------------
# fleet drain: quiesce with everything accounted
# ----------------------------------------------------------------------
def test_fleet_drain_accounts_everything(tiny):
    cfg, model, params = tiny
    prompts = _family_prompts(cfg, n_families=4, per_family=2)
    fleet = FleetRouter(_factory(model, params),
                        fleet={"replicas": 2, "max_replicas": 2})
    for rid, p in sorted(prompts.items()):
        fleet.submit(rid, p, max_new_tokens=6)
    fleet.step()
    out = fleet.drain()
    # every submitted id is in finished or a typed terminal — none lost
    term = fleet.pop_terminated()
    assert set(fleet.finished) | set(term) == set(prompts)
    assert not (set(fleet.finished) & set(term))
    assert set(out["shed"]) == set(term)
    assert fleet.stats["finished"] + fleet.stats["terminated"] \
        == len(prompts)
    assert fleet.leak_report() == {}
    assert out["health"]["draining"] is True
    with pytest.raises(RequestRejected) as ei:
        fleet.submit("late", prompts["f0q0"], max_new_tokens=2)
    assert ei.value.reason == REJECT_DRAINING


# ----------------------------------------------------------------------
# observability: schema-valid fleet events + the /fleet endpoint
# ----------------------------------------------------------------------
def _load_checker():
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    path = os.path.join(repo, "scripts", "check_telemetry_schema.py")
    spec = importlib.util.spec_from_file_location("check_telemetry_schema",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_fleet_event_stream_is_schema_valid(tiny, tmp_path):
    cfg, model, params = tiny
    tel = Telemetry().configure(TelemetryConfig(
        {"enabled": True, "output_path": str(tmp_path),
         "job_name": "fleet"}), rank=0)
    try:
        prompts = _family_prompts(cfg, n_families=3)
        fleet = FleetRouter(_factory(model, params),
                            fleet={"replicas": 2, "max_replicas": 3,
                                   "health_interval": 1},
                            telemetry=tel)
        for rid, p in sorted(prompts.items()):
            fleet.submit(rid, p, max_new_tokens=4)
        fleet.step()
        fleet.kill_replica(next(iter(fleet.replicas)), detail="drill")
        fleet.join()
        fleet.health()
        fleet.drain()
    finally:
        tel.close()
    path = os.path.join(str(tmp_path), "fleet", "events.jsonl")
    checker = _load_checker()
    assert checker.validate_file(path) == []
    with open(path) as f:
        events = [json.loads(ln) for ln in f if ln.strip()]
    names = {e["name"] for e in events if e["kind"] == "fleet"}
    assert {"fleet/spawn", "fleet/route", "fleet/kill",
            "fleet/redispatch", "fleet/respawn"} <= names
    assert names <= set(FLEET_EVENTS)


def test_exporter_fleet_endpoint(tiny, tmp_path):
    cfg, model, params = tiny
    tel = Telemetry().configure(TelemetryConfig(
        {"enabled": True, "output_path": str(tmp_path),
         "job_name": "exp",
         "export": {"enabled": True, "port": 0}}), rank=0)
    try:
        host, port = tel.exporter.address
        base = f"http://{host}:{port}"
        # no router attached yet -> typed 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/fleet")
        assert ei.value.code == 404
        fleet = FleetRouter(_factory(model, params),
                            fleet={"replicas": 2, "max_replicas": 2},
                            telemetry=tel)
        with urllib.request.urlopen(base + "/fleet") as r:
            snap = json.loads(r.read())
        assert snap["n_replicas"] == 2 and snap["n_healthy"] == 2
        assert set(snap["replicas"]) == set(fleet.replicas)
    finally:
        tel.close()


# ----------------------------------------------------------------------
# rendezvous routing: minimal-disruption property
# ----------------------------------------------------------------------
class _StubEngine:
    """Routing-only stand-in: ``_pick`` never steps an engine, so the
    ring-size sweep needs no device work."""

    def __init__(self):
        self.queue = []
        self.n_active = 0
        self.page_size = 8


def test_rendezvous_kill_remaps_only_victims_keys():
    """Property sweep over ring sizes 2–8: killing ONE replica remaps
    exactly the keys it owned (every other key keeps its owner), and a
    respawn under the same replica id re-takes its slot — the full
    pre-kill mapping comes back bit-for-bit."""
    import hashlib as _hl

    for n in range(2, 9):
        fleet = FleetRouter(lambda rid, epoch: _StubEngine(),
                            fleet={"replicas": n, "max_replicas": 8})
        keys = [_hl.blake2b(f"k{i}".encode(), digest_size=16).digest()
                for i in range(200)]
        before = {k: fleet._pick(k).replica_id for k in keys}
        assert len(set(before.values())) == n   # every replica owns keys
        victim = before[keys[0]]
        fleet.kill_replica(victim, detail="property drill")
        moved = 0
        for k in keys:
            now = fleet._pick(k).replica_id
            if before[k] == victim:
                assert now != victim
                moved += 1
            else:
                assert now == before[k]         # untouched keys stay put
        assert moved == sum(1 for o in before.values() if o == victim)
        fleet._ensure_target()                  # respawn re-takes the slot
        assert victim in fleet.replicas
        assert {k: fleet._pick(k).replica_id for k in keys} == before
