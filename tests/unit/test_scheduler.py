"""Scheduler v2 (``serving.scheduler``): chunked prefill + speculative
decoding under SLO classes.

Contracts under test:

* the default ``monolithic`` policy IS the pre-scheduler engine — greedy
  outputs across policies stay token-exact vs the dense oracle;
* the ``chunked`` policy splits long prefills into fixed-token chunks
  interleaved with decode, so a long prompt no longer stalls every
  in-flight decode (max inter-token gap shrinks) and latency-class chat
  TTFT drops on a simulated dispatch clock;
* greedy speculative decoding is bit-identical to the non-speculative
  oracle for a perfect draft (acceptance 1.0) AND an uncorrelated cold
  draft (acceptance near 0) — the verify/correction path earns it;
* SLO classes order admission and chunk scheduling; unknown classes are
  rejected at admission time;
* deadlines are checked at prefill-chunk boundaries: a TTL can cancel a
  request MID-prefill — even between chunks inside one ``step()`` — and
  the engine drains to zero with no page, draft-page, or trace leaks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.robustness import RequestRejected
from deepspeed_tpu.inference.scheduler import SchedulerConfig
from deepspeed_tpu.inference.serving import ServingEngine
from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                              TransformerConfig)


@pytest.fixture(scope="module")
def tiny():
    cfg = TransformerConfig.tiny(hidden_size=64, n_heads=4, n_kv_heads=2)
    model = CausalTransformerLM(cfg)
    params = model.init(jax.random.key(0))
    return cfg, model, params


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def tick(self, dt=1.0):
        self.t += dt


def _dense_greedy(model, params, prompt, n):
    seq = list(prompt)
    for _ in range(n):
        logits = model.apply(params, jnp.asarray(seq)[None, :],
                             train=False)
        seq.append(int(np.argmax(np.asarray(logits[0, -1]))))
    return seq


def _prompts(cfg, seed, lengths):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, cfg.vocab_size, (n,)).tolist()
            for n in lengths]


def _charge_dispatches(eng, cost=1.0):
    """Route every target dispatch through the engine clock: each
    ``_run_step`` call advances the injected FakeClock by ``cost``
    (optionally scaled per token), so scheduling latencies are measured
    in deterministic simulated dispatch time, not CPU wall time."""
    real = eng._run_step

    def charged(ids, tables, lengths, phase="decode"):
        eng._clock.t += cost(ids) if callable(cost) else cost
        return real(ids, tables, lengths, phase=phase)

    eng._run_step = charged


# ----------------------------------------------------------------------
# config + wiring
# ----------------------------------------------------------------------
def test_config_validation():
    with pytest.raises(ValueError):
        SchedulerConfig({"policy": "round-robin"})
    with pytest.raises(ValueError):
        SchedulerConfig({"prefill_chunk_tokens": 0})
    with pytest.raises(ValueError):
        SchedulerConfig({"slo_class_default": "gold"})
    with pytest.raises(ValueError):
        SchedulerConfig({"slo_classes": {"platinum": {}}})
    with pytest.raises(ValueError):
        SchedulerConfig({"speculative": {"enabled": True,
                                         "num_draft_tokens": 0}})
    cfg = SchedulerConfig({"slo_classes":
                           {"latency": {"default_deadline_s": 2.0}}})
    assert cfg.class_deadline_s("latency") == 2.0
    assert cfg.class_deadline_s("throughput") is None


def test_default_policy_is_monolithic(tiny):
    cfg, model, params = tiny
    eng = ServingEngine(model, params, max_batch=1, page_size=8,
                        max_seq=32, dtype=jnp.float32)
    assert eng.scheduler.policy == "monolithic"
    assert eng.scheduler.meta()["speculative"] == 0
    assert eng.health()["scheduler"]["policy"] == "monolithic"


def test_speculative_requires_chunked(tiny):
    cfg, model, params = tiny
    with pytest.raises(ValueError, match="chunked"):
        ServingEngine(
            model, params, max_batch=1, page_size=8, max_seq=32,
            dtype=jnp.float32,
            serving={"scheduler": {"speculative": {"enabled": True}}},
            draft_model=model, draft_params=params)


# ----------------------------------------------------------------------
# chunked prefill: bit-identity + latency behavior
# ----------------------------------------------------------------------
def test_chunked_bit_identical_to_oracle(tiny):
    """Mixed prompt lengths (multi-chunk and sub-chunk) through the
    chunked policy: token-exact vs the dense oracle, clean leak report,
    and the stats prove prefills actually split."""
    cfg, model, params = tiny
    prompts = _prompts(cfg, 0, (5, 20, 3, 33))
    eng = ServingEngine(
        model, params, max_batch=4, page_size=8, max_seq=64,
        dtype=jnp.float32,
        serving={"scheduler": {"policy": "chunked",
                               "prefill_chunk_tokens": 8}})
    outs = eng.generate(prompts, max_new_tokens=6)
    for p, got in zip(prompts, outs):
        assert got == _dense_greedy(model, params, p, 6), p
    assert eng.leak_report() == {}
    stats = eng.scheduler.sched_stats
    assert stats["prefills_split"] == 2          # the 20- and 33-token
    assert stats["prefill_chunks"] > len(prompts)


def test_chunked_interleaves_decode_with_long_prefill(tiny):
    """The head-of-line number: an in-flight chat decode's max
    inter-token gap when a 48-token prompt lands mid-stream.
    Monolithic prefills it as ONE dispatch (the chat's next token waits
    out its whole simulated cost); chunked bounds the stall at one
    8-token chunk per step — max gap at least 2x smaller."""
    cfg, model, params = tiny
    long_p, chat_p = _prompts(cfg, 1, (48, 4))

    def run(sched_cfg):
        clk = FakeClock()
        eng = ServingEngine(model, params, max_batch=2, page_size=8,
                            max_seq=64, dtype=jnp.float32, clock=clk,
                            serving={"scheduler": sched_cfg})
        _charge_dispatches(eng, cost=lambda ids: 0.1 + 0.01 * ids.size)
        eng.add_request("chat", chat_p, max_new_tokens=10)
        eng.step()                       # chat admitted + decoding
        chat = eng.slots[0]
        assert chat is not None and chat.req_id == "chat"
        seen, t_last = len(chat.out), clk.t
        # the long prompt lands now — monolithic charges its whole
        # prefill before control returns; chunked trickles it
        eng.add_request("long", long_p, max_new_tokens=2)
        gaps = []
        while eng.queue or eng.n_active:
            eng.step()
            n = len(chat.out) if eng.slots[0] is chat else 10
            if n > seen:
                gaps.append(clk.t - t_last)
                seen, t_last = n, clk.t
        assert eng.leak_report() == {}
        return max(gaps)

    mono_gap = run({"policy": "monolithic"})
    chunk_gap = run({"policy": "chunked", "prefill_chunk_tokens": 8})
    assert chunk_gap * 2 <= mono_gap, (mono_gap, chunk_gap)


def test_latency_class_ttft_beats_monolithic_on_sim_clock(tiny):
    """The bench's acceptance claim in miniature: a latency-class chat
    request queued behind a long throughput-class prompt on a busy
    engine.  Monolithic admission is class-blind FIFO — the chat's TTFT
    eats the long prompt's one-shot prefill and full service; chunked
    orders admission and chunk scheduling by SLO class, so the chat
    prefills first.  At least 2x lower in simulated dispatch time."""
    cfg, model, params = tiny
    busy_p, long_p, chat_p = _prompts(cfg, 2, (4, 48, 4))

    def run(sched_cfg):
        clk = FakeClock()
        eng = ServingEngine(model, params, max_batch=1, page_size=8,
                            max_seq=64, dtype=jnp.float32, clock=clk,
                            serving={"scheduler": sched_cfg})
        _charge_dispatches(eng, cost=lambda ids: 0.1 + 0.01 * ids.size)
        eng.add_request("busy", busy_p, max_new_tokens=3)
        # both queue behind the busy slot; admit time stamps here
        eng.add_request("long", long_p, max_new_tokens=2,
                        slo_class="throughput")
        eng.add_request("chat", chat_p, max_new_tokens=4,
                        slo_class="latency")
        while eng.queue or eng.n_active:
            eng.step()
        tr = {t.req_id: t for t in eng.tracer.completed}
        assert eng.leak_report() == {}
        return tr["chat"].ttft_ms()

    mono = run({"policy": "monolithic"})
    chunked = run({"policy": "chunked", "prefill_chunk_tokens": 8})
    assert chunked * 2 <= mono, (mono, chunked)


def test_slo_class_orders_admission_and_rejects_unknown(tiny):
    """With one slot busy, a later latency-class arrival is admitted
    ahead of an earlier throughput-class one; an unknown class is a
    typed admission-time rejection."""
    cfg, model, params = tiny
    pa, pb, pc = _prompts(cfg, 3, (4, 5, 6))
    eng = ServingEngine(
        model, params, max_batch=1, page_size=8, max_seq=32,
        dtype=jnp.float32,
        serving={"scheduler": {"policy": "chunked",
                               "prefill_chunk_tokens": 8}})
    eng.add_request("busy", pa, max_new_tokens=2)
    eng.step()
    eng.add_request("batch", pb, max_new_tokens=2,
                    slo_class="throughput")
    eng.add_request("chat", pc, max_new_tokens=2, slo_class="latency")
    while eng.queue or eng.n_active:
        eng.step()
    done = [t.req_id for t in eng.tracer.completed]
    assert done.index("chat") < done.index("batch")
    with pytest.raises(RequestRejected) as e:
        eng.add_request("x", pa, max_new_tokens=2, slo_class="gold")
    assert e.value.reason == "bad_request"


# ----------------------------------------------------------------------
# deadlines at chunk boundaries (satellite: TTL mid-prefill)
# ----------------------------------------------------------------------
def test_deadline_cancels_mid_prefill_and_drains_to_zero(tiny):
    """A 33-token prompt prefilling 8 tokens per step with a 2.5 s TTL
    on a fake clock ticking 1 s per step: the deadline fires BETWEEN
    chunks, the trace closes with the ``deadline`` terminal before any
    first token, and every page and trace is released."""
    cfg, model, params = tiny
    (p,) = _prompts(cfg, 4, (33,))
    clk = FakeClock()
    eng = ServingEngine(
        model, params, max_batch=1, page_size=8, max_seq=64,
        dtype=jnp.float32, clock=clk,
        serving={"scheduler": {"policy": "chunked",
                               "prefill_chunk_tokens": 8}})
    eng.add_request("r", p, max_new_tokens=4, deadline_s=2.5)
    for _ in range(8):
        clk.tick(1.0)
        eng.step()
        if not eng.n_active:
            break
    assert eng.n_active == 0 and not eng.queue
    assert eng.stats["deadline"] == 1
    tr = list(eng.tracer.completed)[-1]
    assert tr.terminal == "deadline" and tr.t_first_token < 0
    # the prefill was cancelled partway: fewer chunks ran than the
    # prompt needs (ceil(33/8) = 5)
    assert 0 < eng.scheduler.sched_stats["prefill_chunks"] < 5
    assert eng.leak_report() == {}
    # every page back in circulation except the reserved scratch page
    assert eng.alloc.available_page_count == eng.alloc.num_pages - 1


def test_deadline_checked_between_chunks_within_one_step(tiny):
    """The chunk-boundary regression: with
    ``max_prefill_chunks_per_step`` covering the whole prompt, all six
    chunks would run inside ONE ``step()`` — the TTL check at each
    chunk boundary must still stop the prefill partway through that
    step, not at the next step boundary."""
    cfg, model, params = tiny
    (p,) = _prompts(cfg, 5, (48,))
    clk = FakeClock()
    eng = ServingEngine(
        model, params, max_batch=1, page_size=8, max_seq=64,
        dtype=jnp.float32, clock=clk,
        serving={"scheduler": {"policy": "chunked",
                               "prefill_chunk_tokens": 8,
                               "max_prefill_chunks_per_step": 8}})
    _charge_dispatches(eng, cost=1.0)    # each chunk costs 1 s
    eng.add_request("r", p, max_new_tokens=2, deadline_s=2.5)
    eng.step()
    assert eng.n_active == 0
    assert eng.stats["deadline"] == 1
    # expired after the chunk that crossed t=2.5 — chunks 4..6 never ran
    assert eng.scheduler.sched_stats["prefill_chunks"] == 3
    assert eng.leak_report() == {}


def test_class_default_ttl_applies(tiny):
    """``slo_classes.latency.default_deadline_s`` stamps a deadline on
    latency-class requests that pass none; throughput requests stay
    deadline-free."""
    cfg, model, params = tiny
    pa, pb = _prompts(cfg, 6, (4, 5))
    clk = FakeClock()
    eng = ServingEngine(
        model, params, max_batch=1, page_size=8, max_seq=32,
        dtype=jnp.float32, clock=clk,
        serving={"scheduler": {
            "policy": "chunked", "prefill_chunk_tokens": 8,
            "slo_classes": {"latency": {"default_deadline_s": 2.0}}}})
    eng.add_request("busy", pa, max_new_tokens=8,
                    slo_class="throughput")
    eng.step()
    eng.add_request("chat", pb, max_new_tokens=2, slo_class="latency")
    for _ in range(10):
        clk.tick(1.0)
        eng.step()
        if not (eng.queue or eng.n_active):
            break
    # the chat request expired in the queue behind the busy slot; the
    # throughput request (no TTL) ran to its full budget
    tr = {t.req_id: t for t in eng.tracer.completed}
    assert tr["chat"].terminal == "deadline"
    assert tr["busy"].terminal == "finish" and \
        tr["busy"].n_generated == 8
    assert eng.leak_report() == {}


# ----------------------------------------------------------------------
# speculative decoding
# ----------------------------------------------------------------------
def test_spec_bit_identical_perfect_and_cold_draft(tiny):
    """Greedy spec-decode vs the dense oracle under a PERFECT draft
    (the target itself: every window accepted, decode steps collapse)
    and a COLD draft (fresh init: acceptance collapses, the correction
    token carries every step) — outputs must be token-exact in both."""
    cfg, model, params = tiny
    cold = model.init(jax.random.key(9))
    prompts = _prompts(cfg, 7, (5, 12, 3))
    oracle = [_dense_greedy(model, params, p, 8) for p in prompts]

    def run(draft_params):
        eng = ServingEngine(
            model, params, max_batch=4, page_size=8, max_seq=64,
            dtype=jnp.float32,
            serving={"scheduler": {
                "policy": "chunked", "prefill_chunk_tokens": 8,
                "speculative": {"enabled": True,
                                "num_draft_tokens": 3}}},
            draft_model=model, draft_params=draft_params)
        outs = eng.generate(prompts, max_new_tokens=8)
        assert eng.leak_report() == {}
        return outs, eng.scheduler.snapshot()

    perfect_outs, perfect = run(params)
    cold_outs, cold_snap = run(cold)
    assert perfect_outs == oracle
    assert cold_outs == oracle
    assert perfect["spec_acceptance_rate"] == 1.0
    assert cold_snap["spec_acceptance_rate"] < 0.5
    # a perfect draft commits whole windows: far fewer decode rounds
    assert perfect["decode_steps"] < cold_snap["decode_steps"]


def test_spec_sampling_requests_ride_nonspeculative(tiny):
    """Temperature > 0 requests keep the host RNG stream: they decode
    token-by-token (window 0) next to speculative greedy neighbours,
    and their outputs match the non-speculative engine bit-for-bit."""
    cfg, model, params = tiny
    pa, pb = _prompts(cfg, 8, (6, 7))

    def run(sched_cfg, spec):
        eng = ServingEngine(
            model, params, max_batch=2, page_size=8, max_seq=64,
            dtype=jnp.float32, serving={"scheduler": sched_cfg},
            draft_model=model if spec else None,
            draft_params=params if spec else None)
        eng.add_request("greedy", pa, max_new_tokens=6)
        eng.add_request("sampled", pb, max_new_tokens=6,
                        temperature=0.8, seed=123)
        out = {}
        while eng.queue or eng.n_active:
            for rid, toks in eng.step().items():
                out.setdefault(rid, []).extend(toks)
        assert eng.leak_report() == {}
        return out

    base = run({"policy": "chunked", "prefill_chunk_tokens": 8}, False)
    spec = run({"policy": "chunked", "prefill_chunk_tokens": 8,
                "speculative": {"enabled": True,
                                "num_draft_tokens": 3}}, True)
    assert spec == base
