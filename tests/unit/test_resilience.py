"""Fault-tolerance layer tests (``deepspeed_tpu/runtime/resilience.py``).

Every recovery path is driven by the deterministic :class:`FaultInjector` —
no flaky sleeps, no real signals, no random corruption.  The acceptance
test at the bottom is the ISSUE's train→save→kill→resume cycle with
injected write failures and a corrupted newest tag, asserting a
bit-identical fp32 trajectory against an unfaulted run.
"""

import json
import os
import shutil

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.monitor.telemetry import get_telemetry
from deepspeed_tpu.parallel import groups
from deepspeed_tpu.runtime.resilience import (BAD_MANIFEST, COMMITTED, LEGACY,
                                              MISSING, NO_MARKER, PARTIAL,
                                              CheckpointCorruptError,
                                              CheckpointTransaction,
                                              DivergenceError,
                                              DivergenceSentinel,
                                              FaultInjector, RetryPolicy,
                                              TrainingPreempted,
                                              atomic_write_text,
                                              build_manifest, gc_tags,
                                              poison_tree, retry_io,
                                              scan_tags, validate_tag,
                                              verify_restored)
from unit.simple_model import SimpleModel, base_config, random_batch

HIDDEN = 16


def _engine(stage=0, **overrides):
    model = SimpleModel(hidden_dim=HIDDEN)
    params = model.init(jax.random.key(0))
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config=base_config(stage, **overrides))
    return engine


def _telemetry_cfg(tmp_path, job):
    return {"enabled": True, "output_path": str(tmp_path), "job_name": job}


def _events(tmp_path, job):
    path = os.path.join(str(tmp_path), job, "events.jsonl")
    get_telemetry().close()  # flush/close the sink before reading
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# ----------------------------------------------------------------------
# retry policy + fault injector
# ----------------------------------------------------------------------
def test_retry_policy_deterministic_backoff():
    a = RetryPolicy(max_retries=5, backoff_secs=0.5, backoff_max_secs=4.0,
                    jitter=0.25, seed=7)
    b = RetryPolicy(max_retries=5, backoff_secs=0.5, backoff_max_secs=4.0,
                    jitter=0.25, seed=7)
    da = [a.delay(i) for i in range(1, 6)]
    db = [b.delay(i) for i in range(1, 6)]
    assert da == db                      # seeded jitter is reproducible
    # exponential base under the cap, jitter only stretches
    assert 0.5 <= da[0] <= 0.5 * 1.25
    assert 1.0 <= da[1] <= 1.0 * 1.25
    assert da[4] <= 4.0 * 1.25           # capped at backoff_max_secs


def test_retry_io_retries_then_succeeds():
    calls = {"n": 0}
    sleeps = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "done"

    policy = RetryPolicy(max_retries=3, backoff_secs=0.01, jitter=0.0,
                         sleep_fn=sleeps.append)
    assert retry_io(flaky, policy, op="t") == "done"
    assert calls["n"] == 3
    assert len(sleeps) == 2              # slept between the failed attempts


def test_retry_io_exhausts_and_runs_cleanup():
    cleanups = []
    policy = RetryPolicy(max_retries=2, backoff_secs=0.0, jitter=0.0,
                         sleep_fn=lambda s: None)

    def always_fail():
        raise OSError("disk on fire")

    with pytest.raises(OSError, match="disk on fire"):
        retry_io(always_fail, policy, op="t",
                 cleanup=lambda: cleanups.append(1))
    assert len(cleanups) == 3            # after every attempt incl. the last


def test_fault_injector_sites_and_counters():
    inj = FaultInjector({"ckpt_save": {"fail_times": 2, "exc": "OSError"},
                         "ckpt_load": {"fail_at": [1],
                                       "exc": "RuntimeError",
                                       "msg": "torn read"},
                         "poison_grads_at": [3, 5]})
    with pytest.raises(OSError):
        inj.check("ckpt_save")
    with pytest.raises(OSError):
        inj.check("ckpt_save")
    inj.check("ckpt_save")               # third call clean
    assert inj.calls("ckpt_save") == 3
    inj.check("ckpt_load")               # call 0 clean
    with pytest.raises(RuntimeError, match="torn read"):
        inj.check("ckpt_load")           # call 1 fails
    inj.check("unknown_site")            # unknown sites never fire
    assert inj.calls("unknown_site") == 1
    assert not inj.poison_grads(2)
    assert inj.poison_grads(3)
    assert not inj.poison_grads(3)       # fires exactly once per step
    inj.reset()
    assert inj.calls("ckpt_save") == 0
    assert inj.poison_grads(3)


def test_fault_injector_from_config_empty_is_none():
    assert FaultInjector.from_config({}) is None
    assert FaultInjector.from_config(None) is None
    assert FaultInjector.from_config({"fs": {"fail_times": 1}}) is not None


def test_fault_sites_doc_lockstep():
    """docs/resilience.md's site table IS the frozen ``FAULT_SITES``
    vocabulary — same names, same order; doc and code cannot drift."""
    import re

    from deepspeed_tpu.runtime.resilience import FAULT_SITES
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    with open(os.path.join(repo, "docs", "resilience.md")) as f:
        doc = f.read()
    documented = re.findall(r"^\| `(\w+)` \|", doc, flags=re.MULTILINE)
    assert tuple(documented) == FAULT_SITES


def test_poison_tree():
    tree = {"a": np.ones((2, 2), np.float32), "b": np.arange(3),
            "c": {"d": np.ones(4, np.float64)}}
    out, n = poison_tree(tree)
    assert n == 2
    assert np.isnan(out["a"]).all() and np.isnan(out["c"]["d"]).all()
    np.testing.assert_array_equal(out["b"], np.arange(3))  # ints untouched


# ----------------------------------------------------------------------
# durable checkpoint protocol (filesystem level)
# ----------------------------------------------------------------------
def _toy_state():
    return {"w": np.arange(8, dtype=np.float32),
            "step": np.asarray(3, np.int32)}


def _commit_toy_tag(root, tag, step=3, checksum=False):
    state = _toy_state()
    txn = CheckpointTransaction(str(root), tag).begin()
    np.savez(os.path.join(txn.tmp_path, "payload.npz"), **state)
    txn.commit(build_manifest(state, tag, step, checksum=checksum))
    return state


def test_transaction_commit_and_validate(tmp_path):
    _commit_toy_tag(tmp_path, "t1")
    status, manifest = validate_tag(str(tmp_path / "t1"))
    assert status == COMMITTED
    assert manifest["global_step"] == 3
    assert [f["path"] for f in manifest["files"]] == ["payload.npz"]
    assert not (tmp_path / ".t1.tmp").exists()   # tmp renamed away


def test_validate_tag_corruption_taxonomy(tmp_path):
    assert validate_tag(str(tmp_path / "nope"))[0] == MISSING

    _commit_toy_tag(tmp_path, "no_marker")
    os.remove(tmp_path / "no_marker" / ".ds_commit")
    assert validate_tag(str(tmp_path / "no_marker"))[0] == NO_MARKER

    _commit_toy_tag(tmp_path, "bad_manifest")
    mpath = tmp_path / "bad_manifest" / "ds_manifest.json"
    m = json.loads(mpath.read_text())
    m["global_step"] = 999                       # content no longer matches
    mpath.write_text(json.dumps(m))              # the self-digest
    assert validate_tag(str(tmp_path / "bad_manifest"))[0] == BAD_MANIFEST

    _commit_toy_tag(tmp_path, "partial")
    os.remove(tmp_path / "partial" / "payload.npz")
    assert validate_tag(str(tmp_path / "partial"))[0] == PARTIAL

    _commit_toy_tag(tmp_path, "truncated")
    p = tmp_path / "truncated" / "payload.npz"
    p.write_bytes(p.read_bytes()[:10])           # torn write: wrong size
    assert validate_tag(str(tmp_path / "truncated"))[0] == PARTIAL

    (tmp_path / "legacy").mkdir()
    (tmp_path / "legacy" / "state.bin").write_bytes(b"old world")
    assert validate_tag(str(tmp_path / "legacy"))[0] == LEGACY


def test_scan_tags_orders_newest_first_and_skips_tmp(tmp_path):
    _commit_toy_tag(tmp_path, "a", step=1)
    _commit_toy_tag(tmp_path, "b", step=5)
    _commit_toy_tag(tmp_path, "c", step=3)
    os.makedirs(tmp_path / ".d.tmp")             # crashed save: invisible
    got = [(t, s) for t, s, _ in scan_tags(str(tmp_path))]
    assert got == [("b", COMMITTED), ("c", COMMITTED), ("a", COMMITTED)]


def test_manifest_checksum_verify(tmp_path):
    state = _toy_state()
    manifest = build_manifest(state, "t", 1, checksum=True)
    manifest["digest"] = "x"                     # digest not needed here
    assert verify_restored(state, manifest)
    state["w"] = state["w"] + 1                  # silent bit-flip analogue
    with pytest.raises(CheckpointCorruptError, match="checksum mismatch"):
        verify_restored(state, manifest)
    # manifests without checksums always pass (no device_get cost paid)
    assert verify_restored(state, build_manifest(state, "t", 1))


def test_gc_keeps_last_k_committed_only(tmp_path):
    for i, tag in enumerate(["t1", "t2", "t3", "t4"]):
        _commit_toy_tag(tmp_path, tag, step=i + 1)
    _commit_toy_tag(tmp_path, "torn", step=99)
    os.remove(tmp_path / "torn" / ".ds_commit")  # evidence: never GC'd
    os.makedirs(tmp_path / ".stale.tmp")
    removed = gc_tags(str(tmp_path), keep_last=2)
    assert sorted(removed) == ["t1", "t2"]
    left = sorted(p.name for p in tmp_path.iterdir())
    assert left == ["t3", "t4", "torn"]          # stale tmp swept too


def test_atomic_write_text(tmp_path):
    path = tmp_path / "latest"
    atomic_write_text(str(path), "tag1")
    atomic_write_text(str(path), "tag2")
    assert path.read_text() == "tag2"
    assert list(tmp_path.iterdir()) == [path]    # no tmp residue


# ----------------------------------------------------------------------
# engine integration: durable save, retry, fallback
# ----------------------------------------------------------------------
def test_save_checkpoint_commits_durable_tag(tmp_path):
    engine = _engine(0)
    engine.train_batch(batch=random_batch(32, HIDDEN, seed=0))
    engine.save_checkpoint(str(tmp_path))
    status, manifest = validate_tag(str(tmp_path / "global_step1"))
    assert status == COMMITTED
    assert manifest["global_step"] == 1
    assert manifest["leaves"]                    # tree structure recorded
    assert (tmp_path / "latest").read_text() == "global_step1"


def test_save_retries_injected_failures_and_emits_fault_events(tmp_path):
    engine = _engine(0, telemetry=_telemetry_cfg(tmp_path, "retryjob"),
                     resilience={"retry_backoff_secs": 0.0,
                                 "retry_jitter": 0.0,
                                 "fault_injection": {
                                     "ckpt_save": {"fail_times": 2}}})
    engine.train_batch(batch=random_batch(32, HIDDEN, seed=0))
    ckpt = tmp_path / "ckpt"
    engine.save_checkpoint(str(ckpt))
    assert engine._injector.calls("ckpt_save") == 3   # 2 failures + success
    assert validate_tag(str(ckpt / "global_step1"))[0] == COMMITTED
    retries = [e for e in _events(tmp_path, "retryjob")
               if e["kind"] == "fault" and e["name"] == "fault/retry"]
    assert [r["attrs"]["attempt"] for r in retries] == [1, 2]


def test_latest_pointer_write_retried_via_fs_site(tmp_path):
    engine = _engine(0, resilience={"retry_backoff_secs": 0.0,
                                    "retry_jitter": 0.0,
                                    "fault_injection": {
                                        "fs": {"fail_times": 1}}})
    engine.train_batch(batch=random_batch(32, HIDDEN, seed=0))
    engine.save_checkpoint(str(tmp_path))
    assert engine._injector.calls("fs") == 2     # 1 failure + success
    assert (tmp_path / "latest").read_text() == "global_step1"


def test_save_fails_after_retry_budget(tmp_path):
    engine = _engine(0, resilience={"max_retries": 1,
                                    "retry_backoff_secs": 0.0,
                                    "fault_injection": {
                                        "ckpt_save": {"fail_times": 5}}})
    engine.train_batch(batch=random_batch(32, HIDDEN, seed=0))
    with pytest.raises(OSError):
        engine.save_checkpoint(str(tmp_path))
    # failed transaction leaves no tmp dir and no visible tag
    assert [p.name for p in tmp_path.iterdir()] == []


@pytest.mark.parametrize("corruption",
                         ["no_marker", "bad_manifest", "truncated"])
def test_fallback_restores_previous_tag(tmp_path, corruption):
    ckpt = tmp_path / "ckpt"
    engine = _engine(0)
    b = [random_batch(32, HIDDEN, seed=i) for i in range(4)]
    engine.train_batch(batch=b[0])
    engine.train_batch(batch=b[1])
    engine.save_checkpoint(str(ckpt))            # global_step2 (good)
    engine.train_batch(batch=b[2])
    engine.train_batch(batch=b[3])
    engine.save_checkpoint(str(ckpt))            # global_step4 (newest)

    bad = ckpt / "global_step4"
    if corruption == "no_marker":
        os.remove(bad / ".ds_commit")
    elif corruption == "bad_manifest":
        (bad / "ds_manifest.json").write_text("{not json")
    else:                                        # truncated state dir
        m = json.loads((bad / "ds_manifest.json").read_text())
        victim = bad / m["files"][0]["path"]
        os.remove(victim)

    groups.reset_mesh()
    engine2 = _engine(0, telemetry=_telemetry_cfg(tmp_path, "fbjob"))
    path, client = engine2.load_checkpoint(str(ckpt))
    assert path is not None
    assert engine2.global_steps == 2             # previous valid tag
    faults = [e for e in _events(tmp_path, "fbjob")
              if e["kind"] == "fault" and e["name"] == "fault/ckpt_fallback"]
    assert len(faults) == 1
    assert faults[0]["attrs"]["to"] == "global_step2"


def test_explicit_corrupt_tag_raises_not_substitutes(tmp_path):
    engine = _engine(0)
    engine.train_batch(batch=random_batch(32, HIDDEN, seed=0))
    engine.save_checkpoint(str(tmp_path), tag="good")
    shutil.copytree(tmp_path / "good", tmp_path / "bad")
    os.remove(tmp_path / "bad" / ".ds_commit")
    groups.reset_mesh()
    engine2 = _engine(0)
    with pytest.raises(CheckpointCorruptError):
        engine2.load_checkpoint(str(tmp_path), tag="bad")


def test_load_retries_injected_load_faults(tmp_path):
    engine = _engine(0)
    engine.train_batch(batch=random_batch(32, HIDDEN, seed=0))
    engine.save_checkpoint(str(tmp_path))
    groups.reset_mesh()
    engine2 = _engine(0, resilience={"retry_backoff_secs": 0.0,
                                     "fault_injection": {
                                         "ckpt_load": {"fail_times": 2}}})
    path, _ = engine2.load_checkpoint(str(tmp_path))
    assert path is not None
    assert engine2.global_steps == 1
    assert engine2._injector.calls("ckpt_load") == 3


def test_keep_last_retention(tmp_path):
    engine = _engine(0, resilience={"keep_last": 2})
    for i in range(4):
        engine.train_batch(batch=random_batch(32, HIDDEN, seed=i))
        engine.save_checkpoint(str(tmp_path))
    tags = sorted(p.name for p in tmp_path.iterdir() if p.is_dir())
    assert tags == ["global_step3", "global_step4"]


def test_checksummed_roundtrip(tmp_path):
    engine = _engine(0, resilience={"checksum": True})
    engine.train_batch(batch=random_batch(32, HIDDEN, seed=0))
    engine.save_checkpoint(str(tmp_path))
    _, manifest = validate_tag(str(tmp_path / "global_step1"))
    assert manifest["checksum"] and \
        all("crc32" in r for r in manifest["leaves"])
    groups.reset_mesh()
    engine2 = _engine(0, resilience={"checksum": True})
    path, _ = engine2.load_checkpoint(str(tmp_path))
    assert path is not None                      # checksums verified on load


def test_legacy_checkpoint_still_loads(tmp_path):
    """Pre-resilience checkpoints (no manifest/marker) stay loadable."""
    engine = _engine(0, resilience={"enabled": False})
    engine.train_batch(batch=random_batch(32, HIDDEN, seed=0))
    engine.save_checkpoint(str(tmp_path))
    assert validate_tag(str(tmp_path / "global_step1"))[0] == LEGACY
    groups.reset_mesh()
    engine2 = _engine(0)                         # resilience ON by default
    path, _ = engine2.load_checkpoint(str(tmp_path))
    assert path is not None
    assert engine2.global_steps == 1


def test_broadcast_client_state_single_process_passthrough():
    """The multihost broadcast is an identity on one process (the 2-proc
    path is covered by the slow test in ``test_multihost.py``)."""
    from deepspeed_tpu.runtime.checkpoint_engine import broadcast_client_state
    cs = {"global_steps": 3, "nested": {"tag": "t"}}
    assert broadcast_client_state(cs) is cs


# ----------------------------------------------------------------------
# checkpoint engine selection (satellite: config was silently ignored)
# ----------------------------------------------------------------------
def test_checkpoint_engine_selected_from_config():
    from deepspeed_tpu.runtime import checkpoint_engine as ce
    e1 = ce.get_checkpoint_engine({"checkpoint": {"engine": "async"}})
    assert isinstance(e1, ce.NebulaCheckpointEngine)
    # a later config with a different engine type rebuilds the cache
    e2 = ce.get_checkpoint_engine({"checkpoint": {"engine": "sync"}})
    assert type(e2) is ce.OrbaxCheckpointEngine
    assert e2 is not e1
    # no-arg call returns the current engine unchanged
    assert ce.get_checkpoint_engine() is e2
    # same type requested again: cached instance is reused
    assert ce.get_checkpoint_engine({"checkpoint": {"engine": "sync"}}) is e2


def test_async_engine_roundtrip(tmp_path):
    engine = _engine(0, checkpoint={"engine": "async"})
    from deepspeed_tpu.runtime import checkpoint_engine as ce
    assert isinstance(ce.get_checkpoint_engine(),
                      ce.NebulaCheckpointEngine)
    engine.train_batch(batch=random_batch(32, HIDDEN, seed=0))
    engine.save_checkpoint(str(tmp_path))
    # the async flush happened before the marker: the tag is committed
    assert validate_tag(str(tmp_path / "global_step1"))[0] == COMMITTED
    groups.reset_mesh()
    engine2 = _engine(0)
    path, _ = engine2.load_checkpoint(str(tmp_path))
    assert path is not None and engine2.global_steps == 1


# ----------------------------------------------------------------------
# preemption handling
# ----------------------------------------------------------------------
def test_preemption_emergency_checkpoint(tmp_path):
    engine = _engine(0, resilience={"preemption_handler": True,
                                    "ckpt_dir": str(tmp_path)})
    engine.train_batch(batch=random_batch(32, HIDDEN, seed=0))
    engine._preempt.request()                    # deterministic signal stand-in
    with pytest.raises(TrainingPreempted):
        engine.train_batch(batch=random_batch(32, HIDDEN, seed=1))
    status, manifest = validate_tag(str(tmp_path / "emergency_step1"))
    assert status == COMMITTED
    assert manifest["global_step"] == 1
    groups.reset_mesh()
    engine2 = _engine(0)
    engine2.load_checkpoint(str(tmp_path), tag="emergency_step1")
    assert engine2.global_steps == 1


def test_preemption_without_ckpt_dir_still_unwinds():
    engine = _engine(0, resilience={"preemption_handler": True})
    engine.train_batch(batch=random_batch(32, HIDDEN, seed=0))
    engine._preempt.request()
    with pytest.raises(TrainingPreempted):
        engine.train_batch(batch=random_batch(32, HIDDEN, seed=1))


# ----------------------------------------------------------------------
# divergence sentinel
# ----------------------------------------------------------------------
def test_sentinel_overflow_streak_unit():
    s = DivergenceSentinel(max_consecutive_skips=3, interval=1)
    for step in range(1, 3):
        s.push(step, loss=np.float32(1.0), overflow=np.asarray(True))
        assert s.poll() is None
    s.push(3, loss=np.float32(1.0), overflow=np.asarray(True))
    assert s.poll() == "halt"
    assert s.reason == "overflow_streak" and s.trip_step == 3
    assert s.poll() is None                      # delivered exactly once
    s.reset()
    s.push(4, loss=np.float32(1.0), overflow=np.asarray(False))
    assert s.poll() is None                      # streak cleared


def test_sentinel_interval_batches_readback():
    s = DivergenceSentinel(max_consecutive_skips=0, interval=4)
    s.push(1, loss=np.float32(np.nan), overflow=None)
    assert s.poll() is None                      # below interval: no fetch
    for step in (2, 3, 4):
        s.push(step, loss=np.float32(1.0), overflow=None)
    assert s.poll() == "halt"                    # batch fetched, NaN found
    assert s.trip_step == 1


def test_poisoned_step_trips_sentinel_halt():
    engine = _engine(0, resilience={"divergence_sentinel": True,
                                    "fault_injection": {
                                        "poison_grads_at": [0]}})
    with pytest.raises(DivergenceError, match="nonfinite_loss"):
        engine.train_batch(batch=random_batch(32, HIDDEN, seed=0))


def test_poisoned_step_auto_restores(tmp_path):
    engine = _engine(0, resilience={"divergence_sentinel": True,
                                    "on_divergence": "restore",
                                    "fault_injection": {
                                        "poison_grads_at": [2]}})
    b = [random_batch(32, HIDDEN, seed=i) for i in range(4)]
    engine.train_batch(batch=b[0])
    engine.train_batch(batch=b[1])
    engine.save_checkpoint(str(tmp_path))        # last-good at step 2
    good = jax.device_get(engine.module_state_dict())
    engine.train_batch(batch=b[2])               # poisoned -> auto-restore
    assert engine.global_steps == 2              # rolled back
    restored = jax.device_get(engine.module_state_dict())
    np.testing.assert_array_equal(good["layer_0"]["w"],
                                  restored["layer_0"]["w"])
    # poison fired once: the retried step is clean and training continues
    loss = float(engine.train_batch(batch=b[2]))
    assert np.isfinite(loss)
    assert engine.global_steps == 3


def test_divergence_halts_when_no_restore_point():
    engine = _engine(0, resilience={"divergence_sentinel": True,
                                    "on_divergence": "restore",
                                    "fault_injection": {
                                        "poison_grads_at": [0]}})
    with pytest.raises(DivergenceError):
        engine.train_batch(batch=random_batch(32, HIDDEN, seed=0))


# ----------------------------------------------------------------------
# dataloader worker retry + ordered drain-through
# ----------------------------------------------------------------------
def _seq_source(n):
    return iter([{"x": np.full((4,), i, np.float32)} for i in range(n)])


def test_prefetch_retry_preserves_order_exactly():
    from deepspeed_tpu.runtime.dataloader import DevicePrefetchIterator
    inj = FaultInjector({"dataloader_next": {"fail_at": [2, 5]}})
    it = DevicePrefetchIterator(_seq_source(6), max_retries=2, injector=inj)
    got = [int(b["x"][0]) for b in it]
    assert got == [0, 1, 2, 3, 4, 5]             # nothing skipped or reordered
    assert inj.calls("dataloader_next") >= 8     # 6 batches + 2 retries
    it.close()


def test_prefetch_non_io_exception_is_never_retried():
    """A non-OSError from the source is not transient: retrying a raised
    generator would surface as a silent StopIteration (truncated epoch)."""
    from deepspeed_tpu.runtime.dataloader import DevicePrefetchIterator

    def feed():
        yield {"x": np.zeros(4, np.float32)}
        raise ValueError("boom in the feed")

    it = DevicePrefetchIterator(feed(), max_retries=5)
    next(it)
    with pytest.raises(ValueError, match="boom in the feed"):
        next(it)
    it.close()


def test_prefetch_fatal_after_retry_budget_drains_in_order():
    from deepspeed_tpu.runtime.dataloader import DevicePrefetchIterator
    # calls 0,1 produce batches; calls 2 and 3 both fail -> one retry
    # (budget 1) then fatal.  The two prefetched batches must still be
    # delivered, in order, before the error surfaces.
    inj = FaultInjector({"dataloader_next": {"fail_at": [2, 3],
                                             "exc": "OSError"}})
    it = DevicePrefetchIterator(_seq_source(6), depth=4, max_retries=1,
                                injector=inj)
    assert int(next(it)["x"][0]) == 0
    assert int(next(it)["x"][0]) == 1
    with pytest.raises(OSError):
        next(it)
    it.close()


def test_prefetch_zero_retries_is_immediately_fatal():
    from deepspeed_tpu.runtime.dataloader import DevicePrefetchIterator
    inj = FaultInjector({"dataloader_next": {"fail_at": [0]}})
    it = DevicePrefetchIterator(_seq_source(3), max_retries=0, injector=inj)
    with pytest.raises(OSError):
        next(it)
    it.close()


def test_engine_prefetcher_survives_transient_worker_fault(tmp_path):
    """End-to-end: async pipeline on, injector raising once in the worker —
    training proceeds through the fault with the retry absorbing it."""
    from unit.simple_model import random_dataset
    engine = _engine(
        0,
        train_micro_batch_size_per_gpu=4,
        async_pipeline={"enabled": True, "prefetch_depth": 2},
        resilience={"dataloader_max_retries": 2,
                    "dataloader_retry_backoff_secs": 0.0,
                    "fault_injection": {
                        "dataloader_next": {"fail_at": [1]}}})
    data = random_dataset(256, HIDDEN, seed=0)
    loader = engine.deepspeed_io(data)
    it = iter(loader)
    losses = [float(engine.train_batch(data_iter=it)) for _ in range(4)]
    assert all(np.isfinite(l) for l in losses)
    assert engine._injector.calls("dataloader_next") >= 5
    loader.close()


# ----------------------------------------------------------------------
# offline fsck
# ----------------------------------------------------------------------
def _load_fsck():
    import importlib.util
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    path = os.path.join(repo, "scripts", "ds_ckpt_fsck.py")
    spec = importlib.util.spec_from_file_location("ds_ckpt_fsck", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_fsck_reports_statuses_and_exit_codes(tmp_path, capsys):
    fsck = _load_fsck()
    _commit_toy_tag(tmp_path, "good", step=2)
    _commit_toy_tag(tmp_path, "torn", step=4)
    os.remove(tmp_path / "torn" / ".ds_commit")
    os.makedirs(tmp_path / ".crash.tmp")
    atomic_write_text(str(tmp_path / "latest"), "good")
    assert fsck.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "good" in out and "no_marker" in out and "stale-tmp" in out

    report = fsck.fsck(str(tmp_path), deep=True)
    assert report["ok"]
    assert report["latest_status"] == COMMITTED
    assert {t["tag"]: t["status"] for t in report["tags"]} == {
        "good": COMMITTED, "torn": NO_MARKER}

    # point latest at the torn tag -> NOT OK, exit 1
    atomic_write_text(str(tmp_path / "latest"), "torn")
    assert fsck.main([str(tmp_path)]) == 1
    # deep mode catches silently-shortened payloads behind a valid size?
    # no — deep re-reads bytes; truncate below recorded size:
    p = tmp_path / "good" / "payload.npz"
    p.write_bytes(p.read_bytes()[:4])
    report = fsck.fsck(str(tmp_path), deep=False)
    assert {t["tag"]: t["status"] for t in report["tags"]}["good"] == PARTIAL


# ----------------------------------------------------------------------
# ACCEPTANCE: faulted train -> save -> kill -> resume, bit-identical fp32
# ----------------------------------------------------------------------
def test_acceptance_faulted_save_kill_resume_bitwise(tmp_path):
    """ISSUE acceptance criterion: the fault injector fails the first two
    checkpoint writes and the newest tag is corrupted post-hoc; a fresh
    process restores the newest *valid* checkpoint and continues with a
    trajectory bit-identical to an unfaulted run."""
    ckpt = tmp_path / "ckpt"
    batches = [random_batch(32, HIDDEN, seed=i) for i in range(6)]

    # unfaulted reference: 2 steps, then record steps 3..6
    ref_engine = _engine(0)
    for b in batches[:2]:
        ref_engine.train_batch(batch=b)
    ref_tail = np.asarray(
        [float(ref_engine.train_batch(batch=b)) for b in batches[2:]],
        dtype=np.float32)

    # faulted run: first two ckpt_save attempts fail (retries absorb them)
    groups.reset_mesh()
    engine = _engine(0, telemetry=_telemetry_cfg(tmp_path, "acceptjob"),
                     resilience={"retry_backoff_secs": 0.0,
                                 "retry_jitter": 0.0,
                                 "fault_injection": {
                                     "ckpt_save": {"fail_times": 2}}})
    for b in batches[:2]:
        engine.train_batch(batch=b)
    engine.save_checkpoint(str(ckpt))            # global_step2: 3rd try wins
    assert engine._injector.calls("ckpt_save") == 3
    for b in batches[2:4]:
        engine.train_batch(batch=b)
    engine.save_checkpoint(str(ckpt))            # global_step4 (newest)
    # corrupt the newest tag (torn commit: marker lost)
    os.remove(ckpt / "global_step4" / ".ds_commit")

    # "kill": a brand-new engine resumes from scratch
    groups.reset_mesh()
    resumed = _engine(0, telemetry=_telemetry_cfg(tmp_path, "resumejob"))
    path, _ = resumed.load_checkpoint(str(ckpt))
    assert path is not None
    assert resumed.global_steps == 2             # newest VALID tag
    got_tail = np.asarray(
        [float(resumed.train_batch(batch=b)) for b in batches[2:]],
        dtype=np.float32)
    np.testing.assert_array_equal(got_tail, ref_tail)  # bit-identical fp32
    faults = [e["name"] for e in _events(tmp_path, "resumejob")
              if e["kind"] == "fault"]
    assert "fault/ckpt_fallback" in faults
