"""Prefix-cache subsystem tests (inference/prefix_cache.py + the
refcounted allocator + the serving wiring).

Oracle discipline: the cache is a FLOPs/latency optimisation, never a
quality knob — every cache-on output must be BIT-IDENTICAL to cache-off
(and to the dense no-cache oracle), including under copy-on-write,
LRU eviction pressure, and injected faults mid-attach."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.prefix_cache import (PrefixCache,
                                                  PrefixCacheConfig)
from deepspeed_tpu.inference.serving import ServingEngine
from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                              TransformerConfig)
from deepspeed_tpu.ops.paged_attention import (PageAllocationError,
                                               PagedAllocator)
from deepspeed_tpu.runtime.resilience import FaultInjector


@pytest.fixture(scope="module")
def tiny():
    cfg = TransformerConfig.tiny(hidden_size=64, n_heads=4, n_kv_heads=2)
    model = CausalTransformerLM(cfg)
    params = model.init(jax.random.key(0))
    return cfg, model, params


def _dense_greedy(model, params, prompt, n):
    seq = list(prompt)
    for _ in range(n):
        logits = model.apply(params, jnp.asarray(seq)[None, :], train=False)
        seq.append(int(np.argmax(np.asarray(logits[0, -1]))))
    return seq


def _engine(model, enabled=True, pc=None, **kw):
    serving = kw.pop("serving", {})
    serving["prefix_cache"] = dict({"enabled": enabled}, **(pc or {}))
    kw.setdefault("max_batch", 2)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_seq", 64)
    params = kw.pop("params")
    return ServingEngine(model, params, dtype=jnp.float32,
                         serving=serving, **kw)


def _shared_prefix_prompts(cfg, seed=0, shared_len=20,
                           suffixes=(5, 9, 3, 7)):
    rng = np.random.default_rng(seed)
    shared = rng.integers(0, cfg.vocab_size, (shared_len,)).tolist()
    ps = [shared + rng.integers(0, cfg.vocab_size, (n,)).tolist()
          for n in suffixes]
    ps.append(list(ps[0]))          # exact repeat: pure full-page reuse
    return ps


# ----------------------------------------------------------------------
# allocator: refcounts, reclaim tier, fault-at-attach atomicity
# ----------------------------------------------------------------------
def test_allocator_refcounted_sharing_and_reclaim_tier():
    al = PagedAllocator(8, 8, 8, reserve_scratch=True)
    a = al.allocate("a", 24)                    # 3 fresh pages
    assert al.ref == {p: 1 for p in a}
    b = al.allocate("b", 32, shared=a[:2])      # share 2, take 2 fresh
    assert b[:2] == a[:2]
    assert al.ref[a[0]] == al.ref[a[1]] == 2
    al.mark_cached(a[0])
    al.mark_cached(a[1])
    al.free_sequence("a")
    # a's shared pages still referenced by b; a's private page is uncached
    # so it went straight back to the free list
    assert al.ref[a[0]] == 1 and a[2] in al.free
    al.free_sequence("b")
    # last reference dropped: cached pages park reclaimable, fresh free
    assert list(al.reclaimable) == [a[0], a[1]]
    assert al.available_page_count == 7 and al.free_page_count == 5
    assert al.audit() == {}
    # a new allocation prefers the free list, then evicts LRU-first
    evicted = []
    al.evict_hook = evicted.append
    al.allocate("c", 8 * 7)                     # needs the whole pool
    assert evicted == [a[0], a[1]]              # oldest first
    assert al.audit() == {}


def test_allocator_fault_at_attach_leaks_nothing():
    inj = FaultInjector({"page_alloc": {"fail_at": [1]}})
    al = PagedAllocator(8, 8, 4, reserve_scratch=True, injector=inj)
    shared = al.allocate("a", 16)
    al.mark_cached(shared[0])
    before = (dict(al.ref), list(al.free), list(al.reclaimable))
    with pytest.raises(PageAllocationError):
        al.allocate("b", 32, shared=shared)
    # the injected fault fired BEFORE any refcount moved: nothing leaked,
    # nothing half-attached
    assert (dict(al.ref), list(al.free), list(al.reclaimable)) == before
    assert "b" not in al.seq_pages
    assert al.audit() == {}
    # the retry (injector exhausted) attaches cleanly
    b = al.allocate("b", 32, shared=shared)
    assert b[:2] == shared and al.ref[shared[0]] == 2
    assert al.audit() == {}


def test_allocator_protect_pins_cow_source():
    al = PagedAllocator(6, 8, 8, reserve_scratch=True)
    pages = al.allocate("a", 8 * 5)             # whole pool
    cow_src = pages[0]
    al.mark_cached(cow_src)
    al.free_sequence("a")                       # cow_src -> reclaimable
    for p in pages[1:]:
        assert p in al.free
    # 4 free + 1 reclaimable; asking for 5 fresh with cow_src protected
    # must fail (it can't evict the pinned page) without leaking its pin
    with pytest.raises(PageAllocationError):
        al.allocate("b", 8 * 5, protect=(cow_src,))
    assert cow_src in al.reclaimable and al.ref.get(cow_src) is None
    # unprotected, the same request evicts it
    al.allocate("b", 8 * 5)
    assert cow_src not in al.reclaimable
    assert al.audit() == {}


# ----------------------------------------------------------------------
# cache index: chain hashing, COW match, capacity, namespace
# ----------------------------------------------------------------------
def test_lookup_walks_chain_and_caps_at_last_token():
    al = PagedAllocator(16, 4, 8, reserve_scratch=True)
    pc = PrefixCache(al, 4)
    toks = list(range(100, 112))                # 3 full pages
    pages = al.allocate("a", 12)
    assert pc.insert(toks, pages) == 3
    # exact prompt: the page holding the LAST token is never attached
    m = pc.lookup(toks)
    assert m.pages == pages[:2] and m.cow_src == pages[2]
    assert m.cow_tokens == 3                    # tokens 8..10, not 11
    # longer prompt sharing the full prefix attaches all 3 pages
    m = pc.lookup(toks + [7, 8])
    assert m.pages == pages and m.cow_src is None
    assert m.cached_tokens(4) == 12
    # diverging at token 5 matches only the first page
    div = toks[:5] + [0] * 7
    assert pc.lookup(div).pages == pages[:1]
    assert pc.audit() == {}


def test_cow_picks_longest_partial_match():
    al = PagedAllocator(16, 8, 8, reserve_scratch=True)
    pc = PrefixCache(al, 8)
    base = list(range(200, 208))
    a = al.allocate("a", 16)
    b = al.allocate("b", 16)
    pc.insert(base + [1, 2, 3, 4, 5, 6, 7, 8], a)
    pc.insert(base + [1, 2, 9, 9, 9, 9, 9, 9], b)
    # both second pages are children of the same chain key; the probe
    # agrees with b's page for 3 tokens, a's for 2 -> COW from b's
    m = pc.lookup(base + [1, 2, 9, 0, 0, 0])
    assert m.pages == [a[0]] or m.pages == [b[0]]   # incumbent first page
    assert m.cow_src == b[1] and m.cow_tokens == 3
    assert pc.stats["cow_copies"] == 1


def test_namespace_isolates_caches():
    al1 = PagedAllocator(8, 4, 8, reserve_scratch=True)
    al2 = PagedAllocator(8, 4, 8, reserve_scratch=True)
    toks = list(range(50, 62))
    c1 = PrefixCache(al1, 4, namespace="modelA/f32/page4")
    c2 = PrefixCache(al2, 4, namespace="modelB/f32/page4")
    c1.insert(toks, al1.allocate("a", 12))
    c2.insert(toks, al2.allocate("a", 12))
    assert set(c1.index) ^ set(c2.index)        # no shared chain keys
    assert not set(c1.index) & set(c2.index)


def test_capacity_cap_evicts_lru_then_stops():
    al = PagedAllocator(32, 4, 16, reserve_scratch=True)
    pc = PrefixCache(al, 4, max_cached_pages=2)
    a = al.allocate("a", 12)
    pc.insert(list(range(300, 312)), a)
    assert pc.cached_page_count == 2            # third page hit the cap
    al.free_sequence("a")                       # both parked reclaimable
    b = al.allocate("b", 8)
    assert pc.insert(list(range(400, 408)), b) == 2
    assert pc.cached_page_count == 2            # LRU evicted to make room
    assert pc.stats["evictions"] == 2
    assert pc.audit() == {} and al.audit() == {}


def test_eviction_hook_unindexes_page():
    al = PagedAllocator(6, 4, 8, reserve_scratch=True)
    pc = PrefixCache(al, 4)
    evicted = []
    pc._on_evict_cb = evicted.append
    a = al.allocate("a", 20)                    # whole 5-page pool
    pc.insert(list(range(20)), a)
    al.free_sequence("a")
    al.allocate("b", 20)                        # forces full reclaim
    assert len(evicted) == 5
    assert pc.index == {} and pc.key_of == {} and pc.children == {}
    assert pc.lookup(list(range(20))).pages == []
    assert pc.audit() == {} and al.audit() == {}


def test_config_validation():
    assert PrefixCacheConfig({}).enabled is False
    with pytest.raises(ValueError):
        PrefixCacheConfig({"max_cached_pages": -1})
    with pytest.raises(ValueError):
        PrefixCacheConfig({"min_prefix_tokens": -2})


# ----------------------------------------------------------------------
# serving engine: bit-identity, COW isolation, leaks, eviction, faults
# ----------------------------------------------------------------------
def test_shared_prefix_batch_bit_identical_and_hits(tiny):
    cfg, model, params = tiny
    prompts = _shared_prefix_prompts(cfg)
    off = _engine(model, params=params, enabled=False)
    expect = off.generate(prompts, max_new_tokens=5)
    eng = _engine(model, params=params, pc={"min_prefix_tokens": 8})
    got = eng.generate(prompts, max_new_tokens=5)
    assert got == expect
    for p, g in zip(prompts, got):
        assert g == _dense_greedy(model, params, p, 5)
    snap = eng.prefix_cache.snapshot()
    assert snap["hits"] >= len(prompts) - 1     # all but the cold first
    assert snap["tokens_reused"] > 0
    assert eng.stats["prefix_hits"] == snap["hits"]
    assert eng.leak_report() == {}


def test_sampled_outputs_bit_identical(tiny):
    cfg, model, params = tiny
    prompts = _shared_prefix_prompts(cfg, seed=3)
    off = _engine(model, params=params, enabled=False)
    expect = off.generate(prompts, max_new_tokens=5, temperature=0.8,
                          top_k=12, top_p=0.9)
    eng = _engine(model, params=params)
    assert eng.generate(prompts, max_new_tokens=5, temperature=0.8,
                        top_k=12, top_p=0.9) == expect
    assert eng.prefix_cache.stats["hits"] > 0


def test_cow_isolation_source_page_untouched(tiny):
    cfg, model, params = tiny
    rng = np.random.default_rng(5)
    base = rng.integers(0, cfg.vocab_size, (18,)).tolist()
    a = base + rng.integers(0, cfg.vocab_size, (4,)).tolist()
    b = base + rng.integers(0, cfg.vocab_size, (6,)).tolist()  # diverges@18
    eng = _engine(model, params=params, max_batch=1)
    out_a = eng.generate([a], max_new_tokens=4)[0]
    assert out_a == _dense_greedy(model, params, a, 4)
    # snapshot every cached page's content, then serve the COW sibling
    cached = sorted(eng.prefix_cache.key_of)
    before = {p: jax.tree_util.tree_map(
        lambda leaf, p=p: np.asarray(leaf[:, p]), eng.caches)
        for p in cached}
    eng.add_request("b", b, max_new_tokens=4)
    done = {}
    while eng.queue or eng.n_active:
        done.update(eng.step())
    assert done["b"] == _dense_greedy(model, params, b, 4)
    assert eng.stats["prefix_cow_copies"] >= 1
    # the shared source pages are bit-identical after the COW write
    for p in cached:
        after = jax.tree_util.tree_map(
            lambda leaf, p=p: np.asarray(leaf[:, p]), eng.caches)
        for x, y in zip(jax.tree_util.tree_leaves(before[p]),
                        jax.tree_util.tree_leaves(after)):
            assert np.array_equal(x, y)
    # ...and the original prompt still replays bit-identically
    assert eng.generate([list(a)], max_new_tokens=4)[0] == out_a
    assert eng.leak_report() == {}


def test_drain_leaves_zero_refcounts(tiny):
    cfg, model, params = tiny
    prompts = _shared_prefix_prompts(cfg, seed=7)
    eng = _engine(model, params=params)
    for i, p in enumerate(prompts):
        eng.add_request(i, p, max_new_tokens=6)
    eng.step()
    eng.step()                                  # leave work in flight
    res = eng.drain()
    assert eng.n_active == 0 and eng.alloc.seq_pages == {}
    assert eng.leak_report() == {}
    assert eng.alloc.audit() == {} and eng.prefix_cache.audit() == {}
    # cached pages survived the drain in the reclaimable tier
    assert res["health"]["prefix_cache"]["cached_pages"] > 0
    assert eng.alloc.available_page_count == eng.alloc.num_pages - 1


def test_lru_eviction_under_pool_pressure(tiny):
    cfg, model, params = tiny
    rng = np.random.default_rng(9)
    prompts = [rng.integers(0, cfg.vocab_size, (20,)).tolist()
               for _ in range(4)]               # distinct: no reuse
    eng = _engine(model, params=params, max_batch=1, max_seq=32,
                  num_pages=9)                  # 8 usable pages
    for i, p in enumerate(prompts):
        got = eng.generate([p], max_new_tokens=4)[0]
        assert got == _dense_greedy(model, params, p, 4), i
    assert eng.stats["prefix_evictions"] > 0    # pool forced reclaims
    assert eng.prefix_cache.audit() == {} and eng.alloc.audit() == {}
    assert eng.leak_report() == {}


def test_page_alloc_fault_mid_attach_recovers_bit_identical(tiny):
    cfg, model, params = tiny
    prompts = _shared_prefix_prompts(cfg, seed=11)
    off = _engine(model, params=params, enabled=False)
    expect = off.generate(prompts, max_new_tokens=5)
    # allocation call 0 is the cold first request; 1 and 2 fault while
    # attaching SHARED pages — the refcounts must not leak and the retry
    # must serve bit-identically
    inj = FaultInjector({"page_alloc": {"fail_at": [1, 2]}})
    eng = _engine(model, params=params, injector=inj)
    got = eng.generate(prompts, max_new_tokens=5)
    assert got == expect
    assert eng.stats["step_faults"] >= 2
    assert eng.prefix_cache.stats["hits"] > 0   # reuse still happened
    eng.drain()
    assert eng.leak_report() == {}
    assert eng.alloc.audit() == {}


def test_serve_step_faults_compose_with_cache(tiny):
    cfg, model, params = tiny
    prompts = _shared_prefix_prompts(cfg, seed=13)
    off = _engine(model, params=params, enabled=False)
    expect = off.generate(prompts, max_new_tokens=4)
    eng = _engine(model, params=params,
                  serving={"fault_injection":
                           {"serve_step": {"fail_at": [1, 3]}}})
    assert eng.generate(prompts, max_new_tokens=4) == expect
    assert eng.leak_report() == {}


def test_admission_counts_reclaimable_as_available(tiny):
    cfg, model, params = tiny
    rng = np.random.default_rng(15)
    warm = rng.integers(0, cfg.vocab_size, (40,)).tolist()
    eng = _engine(model, params=params, max_batch=1,
                  serving={"free_page_low_watermark": 4,
                           "overload_policy": "reject"})
    eng.generate([warm], max_new_tokens=8)
    # the warm cache parked enough pages reclaimable that the FREE list is
    # below the watermark — but they are one eviction from free, so
    # admission must not read this as page pressure
    assert eng.alloc.free_page_count <= 4
    assert eng.alloc.available_page_count > 4
    eng.add_request("next", warm[:10], max_new_tokens=4)   # must not raise
    while eng.queue or eng.n_active:
        eng.step()
    assert eng.leak_report() == {}


def test_disabled_cache_is_inert(tiny):
    cfg, model, params = tiny
    eng = _engine(model, params=params, enabled=False)
    assert eng.prefix_cache is None
    p = _shared_prefix_prompts(cfg, seed=17)[0]
    assert eng.generate([p], max_new_tokens=4)[0] == \
        _dense_greedy(model, params, p, 4)
    assert eng.alloc.reclaimable == {} and eng.alloc.cached == set()
    assert eng.leak_report() == {}


def test_health_exposes_frozen_prefix_gauges(tiny, tmp_path):
    from deepspeed_tpu.monitor.telemetry import Telemetry
    from deepspeed_tpu.runtime.config import TelemetryConfig
    cfg, model, params = tiny
    tel = Telemetry().configure(
        TelemetryConfig({"enabled": True, "output_path": str(tmp_path),
                         "job_name": "pc"}), rank=0)
    eng = _engine(model, params=params, telemetry=tel)
    eng.generate(_shared_prefix_prompts(cfg, seed=19), max_new_tokens=4)
    snap = eng.health()
    assert snap["prefix_cache"]["hits"] > 0
    reg = tel.registry
    assert reg.gauge("serve/prefix_hit_rate").value > 0
    assert reg.gauge("serve/prefix_tokens_reused").value > 0
    assert reg.gauge("serve/prefix_cached_pages").value > 0
    tel.close()
