"""bf16 gradient tree / GAS carry (``data_types.grad_accum_dtype``).

Reference parity: DeepSpeed reads ``data_types.grad_accum_dtype``
(reference runtime/config.py:943) to pick the dtype gradients are
accumulated in.  Here the knob sets the dtype of the whole grad tree —
including the ``lax.scan`` GAS carry — halving grad HBM, which is what
(together with bf16 Adam moments) fits a >=1B-param train state on one
16 GB chip.  Adam math, the grad norm, and clipping still run fp32
(engine._global_norm_f32 upcasts inside the reduction).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.config import (DeepSpeedConfig,
                                          DeepSpeedConfigError)
from unit.simple_model import SimpleModel, base_config, random_batch

HIDDEN = 16


def _run(grad_accum_dtype, steps=25, gas=2, clip=None):
    model = SimpleModel(hidden_dim=HIDDEN)
    params = model.init(jax.random.key(0))
    cfg = base_config(0)
    cfg["optimizer"] = {"type": "AdamW", "params": {"lr": 1e-2}}
    cfg["gradient_accumulation_steps"] = gas
    if grad_accum_dtype:
        cfg["data_types"] = {"grad_accum_dtype": grad_accum_dtype}
    if clip:
        cfg["gradient_clipping"] = clip
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config=cfg)
    mb = random_batch(32, HIDDEN)
    batch = jax.tree_util.tree_map(
        lambda x: np.stack([x] * gas), mb) if gas > 1 else mb
    return [float(engine.train_batch(batch=batch)) for _ in range(steps)]


def test_bf16_grad_accum_tracks_fp32_trajectory():
    l32 = _run(None)
    l16 = _run("bfloat16")
    assert l16[-1] < l16[0] * 0.9          # still trains
    np.testing.assert_allclose(l16[-1], l32[-1], rtol=0.1, atol=0.05)


def test_bf16_grad_accum_with_clipping():
    # the fp32-norm clip path must engage without dtype errors
    losses = _run("bfloat16", steps=10, clip=0.5)
    assert losses[-1] < losses[0]


def test_grads_actually_ride_bf16():
    """Trace the compiled train step and assert the GAS scan carries a
    bf16 grad tree (not an fp32 one that is merely cast at the end)."""
    model = SimpleModel(hidden_dim=HIDDEN)
    params = model.init(jax.random.key(0))
    cfg = base_config(0)
    cfg["optimizer"] = {"type": "AdamW", "params": {"lr": 1e-2}}
    cfg["gradient_accumulation_steps"] = 2
    cfg["data_types"] = {"grad_accum_dtype": "bf16"}
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config=cfg)
    step = engine._build_train_step(gas=2)
    mb = random_batch(4, HIDDEN)
    batch = jax.tree_util.tree_map(lambda x: np.stack([x, x]), mb)
    jaxpr = jax.make_jaxpr(step)(engine.state, batch)
    scans = [e for e in jaxpr.jaxpr.eqns if e.primitive.name == "scan"]
    assert scans, "GAS lax.scan not found in train step"
    carry_dtypes = {v.aval.dtype for s in scans for v in s.outvars
                    if hasattr(v.aval, "dtype") and v.aval.ndim >= 2}
    assert jnp.dtype(jnp.bfloat16) in carry_dtypes, carry_dtypes


def test_config_parses_aliases_and_rejects_junk():
    base = {"train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}}
    for alias, want in [("bf16", "bfloat16"), ("bfloat16", "bfloat16"),
                        ("fp32", "float32"), ("float32", "float32")]:
        cfg = DeepSpeedConfig(
            dict(base, data_types={"grad_accum_dtype": alias}), world_size=1)
        assert cfg.grad_accum_dtype == want
    cfg = DeepSpeedConfig(dict(base), world_size=1)
    assert cfg.grad_accum_dtype is None
    with pytest.raises(DeepSpeedConfigError, match="grad_accum_dtype"):
        DeepSpeedConfig(
            dict(base, data_types={"grad_accum_dtype": "fp8"}), world_size=1)
