"""Checkpoint round-trip tests (parity model: reference ``unit/checkpoint/*``:
save/load, optimizer state, elastic reshard)."""

import jax
import numpy as np
import pytest

import deepspeed_tpu
from unit.simple_model import SimpleModel, base_config, random_batch

HIDDEN = 16


def _engine(stage=0, **overrides):
    model = SimpleModel(hidden_dim=HIDDEN)
    params = model.init(jax.random.key(0))
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config=base_config(stage, **overrides))
    return engine


@pytest.mark.parametrize("stage", [0, 1, 3])
def test_save_load_roundtrip(tmp_path, stage):
    engine = _engine(stage)
    for i in range(3):
        engine.train_batch(batch=random_batch(32, HIDDEN, seed=i))
    engine.save_checkpoint(str(tmp_path), tag="ckpt")
    ref_params = jax.device_get(engine.module_state_dict())

    from deepspeed_tpu.parallel import groups
    groups.reset_mesh()
    engine2 = _engine(stage)
    engine2.load_checkpoint(str(tmp_path), tag="ckpt")
    loaded = jax.device_get(engine2.module_state_dict())
    for k in ref_params:
        np.testing.assert_array_equal(ref_params[k]["w"], loaded[k]["w"])
    assert engine2.global_steps == 3

    # resumed training matches
    b = random_batch(32, HIDDEN, seed=99)
    l1 = float(engine.train_batch(batch=b))
    l2 = float(engine2.train_batch(batch=b))
    np.testing.assert_allclose(l1, l2, rtol=1e-6)


def test_latest_tag(tmp_path):
    engine = _engine(0)
    engine.train_batch(batch=random_batch(32, HIDDEN))
    engine.save_checkpoint(str(tmp_path))
    assert (tmp_path / "latest").exists()
    from deepspeed_tpu.parallel import groups
    groups.reset_mesh()
    engine2 = _engine(0)
    path, client = engine2.load_checkpoint(str(tmp_path))
    assert path is not None
    assert engine2.global_steps == 1


def test_elastic_reshard_stage3_to_stage0(tmp_path):
    """Save ZeRO-3 (sharded), load into stage-0 (replicated) — the reference's
    elastic-checkpoint / zero_to_fp32 consolidation path."""
    engine = _engine(3)
    engine.train_batch(batch=random_batch(32, HIDDEN))
    engine.save_checkpoint(str(tmp_path), tag="t")
    ref = jax.device_get(engine.module_state_dict())

    from deepspeed_tpu.parallel import groups
    groups.reset_mesh()
    engine2 = _engine(0)
    engine2.load_checkpoint(str(tmp_path), tag="t")
    loaded = jax.device_get(engine2.module_state_dict())
    np.testing.assert_array_equal(ref["layer_0"]["w"], loaded["layer_0"]["w"])


@pytest.mark.parametrize("mesh_b", [{"tp": 4, "fsdp": 2}, {"fsdp": 8},
                                    {"tp": 2, "fsdp": 4}])
def test_mesh_reshape_roundtrip(tmp_path, mesh_b):
    """Universal-checkpoint reshape (reference ``checkpoint/reshape_meg_2d.py``
    / ``reshape_3d_utils.py`` + ``universal_checkpoint.py:13``): save under
    mesh {tp=2, fsdp=4}, load under a different tp/fsdp factorisation, and
    the training trajectory must continue as if the mesh never changed."""
    from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                                  TransformerConfig)
    from deepspeed_tpu.parallel import groups

    cfg = TransformerConfig.tiny(n_layers=2, n_heads=4)
    rng = np.random.default_rng(0)
    batches = [{"input_ids": rng.integers(0, cfg.vocab_size, (8, 32))}
               for _ in range(4)]

    def make_engine(mesh):
        groups.reset_mesh()
        model = CausalTransformerLM(cfg)
        params = model.init(jax.random.key(0))
        engine, *_ = deepspeed_tpu.initialize(
            model=model, model_parameters=params,
            config={"train_micro_batch_size_per_gpu": 8,
                    "mesh": dict(mesh),
                    "zero_optimization": {"stage": 3},
                    "optimizer": {"type": "AdamW",
                                  "params": {"lr": 1e-3}}})
        return engine

    # mesh A: two steps, save, then one more step -> reference loss
    engine = make_engine({"tp": 2, "fsdp": 4})
    for b in batches[:2]:
        engine.train_batch(batch=b)
    engine.save_checkpoint(str(tmp_path), tag="reshape")
    ref_next = [float(engine.train_batch(batch=b)) for b in batches[2:]]

    # mesh B: load and continue — same trajectory
    engine2 = make_engine(mesh_b)
    engine2.load_checkpoint(str(tmp_path), tag="reshape")
    assert engine2.global_steps == 2
    wq = engine2.state.params["layers"]["wq"]
    if mesh_b.get("tp", 1) > 1:
        assert "tp" in str(wq.sharding.spec), wq.sharding
    got_next = [float(engine2.train_batch(batch=b)) for b in batches[2:]]
    # fsdp/tp regrouping reorders float reductions -> allclose, not bitwise
    np.testing.assert_allclose(got_next, ref_next, rtol=2e-5, atol=1e-6)
    groups.reset_mesh()


def test_load_module_only(tmp_path):
    engine = _engine(1)
    engine.train_batch(batch=random_batch(32, HIDDEN))
    engine.save_checkpoint(str(tmp_path), tag="t")
    from deepspeed_tpu.parallel import groups
    groups.reset_mesh()
    engine2 = _engine(1)
    engine2.load_checkpoint(str(tmp_path), tag="t", load_module_only=True)
    # params match, optimizer state fresh (zeros)
    ref = jax.device_get(engine.module_state_dict())
    loaded = jax.device_get(engine2.module_state_dict())
    np.testing.assert_array_equal(ref["layer_0"]["w"], loaded["layer_0"]["w"])
