"""Checkpoint round-trip tests (parity model: reference ``unit/checkpoint/*``:
save/load, optimizer state, elastic reshard)."""

import jax
import numpy as np
import pytest

import deepspeed_tpu
from unit.simple_model import SimpleModel, base_config, random_batch

HIDDEN = 16


def _engine(stage=0, **overrides):
    model = SimpleModel(hidden_dim=HIDDEN)
    params = model.init(jax.random.key(0))
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config=base_config(stage, **overrides))
    return engine


@pytest.mark.parametrize("stage", [0, 1, 3])
def test_save_load_roundtrip(tmp_path, stage):
    engine = _engine(stage)
    for i in range(3):
        engine.train_batch(batch=random_batch(32, HIDDEN, seed=i))
    engine.save_checkpoint(str(tmp_path), tag="ckpt")
    ref_params = jax.device_get(engine.module_state_dict())

    from deepspeed_tpu.parallel import groups
    groups.reset_mesh()
    engine2 = _engine(stage)
    engine2.load_checkpoint(str(tmp_path), tag="ckpt")
    loaded = jax.device_get(engine2.module_state_dict())
    for k in ref_params:
        np.testing.assert_array_equal(ref_params[k]["w"], loaded[k]["w"])
    assert engine2.global_steps == 3

    # resumed training matches
    b = random_batch(32, HIDDEN, seed=99)
    l1 = float(engine.train_batch(batch=b))
    l2 = float(engine2.train_batch(batch=b))
    np.testing.assert_allclose(l1, l2, rtol=1e-6)


def test_latest_tag(tmp_path):
    engine = _engine(0)
    engine.train_batch(batch=random_batch(32, HIDDEN))
    engine.save_checkpoint(str(tmp_path))
    assert (tmp_path / "latest").exists()
    from deepspeed_tpu.parallel import groups
    groups.reset_mesh()
    engine2 = _engine(0)
    path, client = engine2.load_checkpoint(str(tmp_path))
    assert path is not None
    assert engine2.global_steps == 1


def test_elastic_reshard_stage3_to_stage0(tmp_path):
    """Save ZeRO-3 (sharded), load into stage-0 (replicated) — the reference's
    elastic-checkpoint / zero_to_fp32 consolidation path."""
    engine = _engine(3)
    engine.train_batch(batch=random_batch(32, HIDDEN))
    engine.save_checkpoint(str(tmp_path), tag="t")
    ref = jax.device_get(engine.module_state_dict())

    from deepspeed_tpu.parallel import groups
    groups.reset_mesh()
    engine2 = _engine(0)
    engine2.load_checkpoint(str(tmp_path), tag="t")
    loaded = jax.device_get(engine2.module_state_dict())
    np.testing.assert_array_equal(ref["layer_0"]["w"], loaded["layer_0"]["w"])


def test_load_module_only(tmp_path):
    engine = _engine(1)
    engine.train_batch(batch=random_batch(32, HIDDEN))
    engine.save_checkpoint(str(tmp_path), tag="t")
    from deepspeed_tpu.parallel import groups
    groups.reset_mesh()
    engine2 = _engine(1)
    engine2.load_checkpoint(str(tmp_path), tag="t", load_module_only=True)
    # params match, optimizer state fresh (zeros)
    ref = jax.device_get(engine.module_state_dict())
    loaded = jax.device_get(engine2.module_state_dict())
    np.testing.assert_array_equal(ref["layer_0"]["w"], loaded["layer_0"]["w"])
