"""ZeRO stage tests.

Parity model: reference ``tests/unit/runtime/zero/test_zero.py`` — ZeRO runs
must produce the same training trajectory as the unsharded (stage-0, world-1)
baseline, while actually partitioning state across the mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.parallel.topology import TopologyConfig, build_mesh
from deepspeed_tpu.runtime.zero.stage_plan import ZeroShardingPlan, add_axis_to_spec

from unit.simple_model import SimpleModel, base_config, random_batch

HIDDEN = 16


def _train(stage, steps=5, seed=0, **cfg_overrides):
    model = SimpleModel(hidden_dim=HIDDEN)
    params = model.init(jax.random.key(seed))
    config = base_config(stage, **cfg_overrides)
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config=config)
    losses = []
    for i in range(steps):
        loss = engine.train_batch(batch=random_batch(32, HIDDEN, seed=i))
        losses.append(float(loss))
    final = jax.device_get(engine.module_state_dict())
    return losses, final, engine


@pytest.mark.parametrize("stage", [1, 2, 3])
def test_stage_matches_stage0_baseline(stage):
    losses0, params0, _ = _train(0)
    from deepspeed_tpu.parallel import groups
    groups.reset_mesh()
    losses, params, _ = _train(stage)
    np.testing.assert_allclose(losses, losses0, rtol=2e-4, atol=2e-5)
    for k in params0:
        np.testing.assert_allclose(
            params["layer_0"]["w"], params0["layer_0"]["w"], rtol=2e-4, atol=2e-5)


def test_stage3_params_actually_sharded():
    # tiny params are all below the default persistence threshold; zero it so
    # partitioning is observable
    _, _, engine = _train(
        3, zero_optimization={"stage": 3, "param_persistence_threshold": 0})
    w = engine.state.params["layer_0"]["w"]
    assert "fsdp" in str(w.sharding.spec)
    # each shard holds 1/8 of the rows
    shard_shapes = {s.data.shape for s in w.addressable_shards}
    assert shard_shapes == {(HIDDEN // 8, HIDDEN)}


def test_stage1_opt_state_sharded_params_replicated():
    _, _, engine = _train(1)
    w = engine.state.params["layer_0"]["w"]
    assert "fsdp" in str(w.sharding.spec)  # master fp32 partitioned (stage>=1)
    leaves = jax.tree_util.tree_leaves(engine.state.opt_state)
    big = [l for l in leaves if getattr(l, "ndim", 0) >= 2]
    assert any("fsdp" in str(l.sharding.spec) for l in big)


def test_stage3_persistence_default_keeps_tiny_replicated():
    """With the reference-default 100k threshold, sub-threshold leaves stay
    replicated (reference param_persistence_threshold semantics)."""
    _, _, engine = _train(3)
    w = engine.state.params["layer_0"]["w"]
    assert "fsdp" not in str(w.sharding.spec)


def test_stage0_fully_replicated():
    _, _, engine = _train(0)
    w = engine.state.params["layer_0"]["w"]
    assert "fsdp" not in str(w.sharding.spec)


def test_loss_decreases_with_fixed_batch():
    model = SimpleModel(hidden_dim=HIDDEN)
    params = model.init(jax.random.key(0))
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config=base_config(3))
    batch = random_batch(32, HIDDEN, seed=0)
    losses = [float(engine.train_batch(batch=batch)) for _ in range(25)]
    assert losses[-1] < losses[0] * 0.5


# ----------------------------------------------------------------------
# sharding-plan unit tests
# ----------------------------------------------------------------------
def test_add_axis_to_spec_picks_largest_divisible():
    spec = add_axis_to_spec(None, (4, 64), "fsdp", 8, {"fsdp": 8})
    assert spec == P(None, "fsdp")


def test_add_axis_to_spec_respects_existing():
    spec = add_axis_to_spec(P(None, "tp"), (64, 8), "fsdp", 8,
                            {"fsdp": 8, "tp": 2})
    assert spec == P("fsdp", "tp")


def test_add_axis_to_spec_indivisible_stays():
    spec = add_axis_to_spec(None, (7, 3), "fsdp", 8, {"fsdp": 8})
    assert spec == P()


def test_persistence_threshold_keeps_small_replicated():
    mesh = build_mesh(TopologyConfig())
    plan = ZeroShardingPlan(mesh, stage=3, param_persistence_threshold=1000)
    params = {"big": jnp.zeros((64, 64)), "small": jnp.zeros((8, 8))}
    specs = plan.param_specs(params)
    assert "fsdp" in str(specs["big"])
    assert specs["small"] == P()


def test_opt_state_specs_align(mesh_1d):
    plan = ZeroShardingPlan(mesh_1d, stage=1)
    params = {"w": jnp.zeros((64, 16))}
    tx = optax.adam(1e-3)
    specs = plan.opt_state_specs(tx, params)
    flat = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P))
    assert any(s == P("fsdp", None) for s in flat)
