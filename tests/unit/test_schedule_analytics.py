"""Analytic WORK metrics for the perf-motivated schedules.

VERDICT r3 item 9: zig-zag ring and interleaved PP had correctness
evidence (output equality) but nothing asserting the *work* distribution
they exist to improve.  These tests pin the analytic invariants:

* zig-zag SP: per-device computed causal work is balanced (the contiguous
  layout's device n-1 does ~n× device 0's FLOPs — the whole point of the
  permutation, ``ops/ring_attention.py`` zigzag_perm);
* interleaved PP: the bubble shrinks ~V× vs plain scheduling at the same
  (M, P) (reference Megatron interleaved 1F1B claim; ``pipeline.py``
  pipeline_interleaved clock).
"""

import numpy as np
import pytest

from deepspeed_tpu.ops.ring_attention import zigzag_perm


# ----------------------------------------------------------------------
# zig-zag ring: causal work balance
# ----------------------------------------------------------------------
def _causal_pairs_per_device(perm, n, S):
    """Exact causal (q >= k) pair count each device computes when device d
    owns permuted-token slice [d*S/n, (d+1)*S/n) and sees every kv chunk
    over the ring (the ring rotates all kv past all devices, so device
    work = causal pairs with q in its slice, k anywhere)."""
    c = S // n
    counts = []
    for d in range(n):
        q_glob = perm[d * c:(d + 1) * c]          # global positions owned
        counts.append(int(sum(q + 1 for q in q_glob)))  # k <= q, all kv
    return counts


def _computed_subblocks_per_device(n):
    """Block-level work under the kernel's skip rule: device d holds
    chunks (d, 2n-1-d); a (q_chunk, k_chunk) sub-block is computed iff
    q_cid >= k_cid (fully-future blocks are lax.cond-skipped —
    ``_zz_fwd_local``).  Over a full ring pass every kv chunk visits
    every device."""
    counts = []
    for d in range(n):
        q_cids = (d, 2 * n - 1 - d)
        computed = sum(1 for q_cid in q_cids for k_cid in range(2 * n)
                       if q_cid >= k_cid)
        counts.append(computed)
    return counts


@pytest.mark.parametrize("n", [2, 4, 8])
def test_zigzag_block_work_balanced(n):
    zz = _computed_subblocks_per_device(n)
    # every device computes exactly 2n+1 of its 4n sub-blocks
    assert all(c == 2 * n + 1 for c in zz), zz
    # contiguous layout (device d = chunk d of n): d+1 computed blocks →
    # device n-1 does n× device 0's block work
    contiguous = [d + 1 for d in range(n)]
    assert max(contiguous) == n * min(contiguous)


@pytest.mark.parametrize("n,S", [(2, 32), (4, 64), (8, 128)])
def test_zigzag_pair_work_balanced(n, S):
    """FLOP-level balance from the ACTUAL permutation: max/min causal-pair
    imbalance stays within one chunk's self-block, while contiguous is
    ~(2n-1)×."""
    perm, inv = zigzag_perm(S, n)
    # sanity: perm is a permutation and inv inverts it
    assert sorted(perm.tolist()) == list(range(S))
    np.testing.assert_array_equal(perm[inv], np.arange(S))

    zz = _causal_pairs_per_device(perm.tolist(), n, S)
    assert max(zz) - min(zz) <= (S // (2 * n)) ** 2, zz
    contiguous = _causal_pairs_per_device(list(range(S)), n, S)
    assert max(contiguous) / min(contiguous) > (2 * n - 1) * 0.9
    # both layouts cover the identical causal triangle
    assert sum(zz) == sum(contiguous) == S * (S + 1) // 2


# ----------------------------------------------------------------------
# interleaved PP: bubble ticks shrink ~V× (simulated on the real clock)
# ----------------------------------------------------------------------
def _simulate_interleaved_busy(M, Pn, V):
    """Replay ``pipeline_interleaved``'s tick rule with validity flags:
    counts per-stage ticks holding a REAL microbatch activation, plus
    checks the exit-tick formula."""
    groups_inject = -(-M // Pn)
    T = (groups_inject * V) * Pn + (Pn - 1)
    valid = np.zeros(Pn, bool)            # does slot s hold a live mb?
    mb_of = np.full(Pn, -1)               # which mb
    chunk_of = np.full(Pn, -1)            # which virtual chunk
    busy = np.zeros(Pn, int)
    exits = {}                            # mb -> tick its chunk V-1 exited
    for t in range(T):
        G, r = divmod(t, Pn)
        mb_new = (G // V) * Pn + r
        inject = (G % V == 0) and (mb_new < M)
        if inject:
            valid[0], mb_of[0], chunk_of[0] = True, mb_new, 0
        elif valid[0]:
            chunk_of[0] += 1              # wraparound: next virtual chunk
        busy += valid
        # exit: slot P-1 finishing chunk V-1
        if valid[Pn - 1] and chunk_of[Pn - 1] == V - 1:
            exits.setdefault(int(mb_of[Pn - 1]), t)
        # roll: slot s -> s+1; slot P-1 wraps into slot 0
        valid = np.roll(valid, 1)
        mb_of = np.roll(mb_of, 1)
        chunk_of = np.roll(chunk_of, 1)
        if valid[0] and chunk_of[0] >= V - 1 and V > 1:
            # chunk V-1 wrapped around after exiting: slot 0 must not
            # treat it as live unless it still has chunks to run
            valid[0] = chunk_of[0] < V - 1 or False
        chunk_of[0] = chunk_of[0] if valid[0] else -1
    return T, busy, exits


@pytest.mark.parametrize("M,Pn,V", [(8, 4, 2), (16, 4, 4), (8, 2, 2)])
def test_interleaved_bubble_shrinks_vx(M, Pn, V):
    T, busy, exits = _simulate_interleaved_busy(M, Pn, V)
    assert T == (-(-M // Pn) * V) * Pn + (Pn - 1)
    # every stage runs M·V useful chunk-ticks
    assert busy.max() == M * V, busy
    # normalized time units: interleaved tick costs 1/V of a plain tick
    # (1/V of the layers) → total wall = T/V, useful = M, bubble:
    bubble_int = (T - M * V) / V
    bubble_plain = (M + Pn - 1) - M        # gpipe/1F1B fwd clock: P-1
    assert bubble_int == pytest.approx(bubble_plain / V), \
        (bubble_int, bubble_plain)
    # exit-tick formula used by pipeline_interleaved to slice outputs
    for m in range(M):
        want = ((m // Pn) * V + V - 1) * Pn + (m % Pn) + (Pn - 1)
        assert exits[m] == want, (m, exits[m], want)


def test_true_1f1b_residual_ring_bound():
    """True 1F1B's documented memory contract: the VJP residual ring holds
    2P-1 slots regardless of M (vs gpipe's O(M)) — the analytic form of
    the compiled-memory test (test_pipe.py asserts the compiled bytes)."""
    for Pn in (2, 4, 8):
        K = 2 * Pn - 1
        # residual for (stage s, microbatch m) lives 2(P-1-s) ticks; the
        # longest-lived (s=0) fits the ring with one slot to spare
        max_live = 2 * (Pn - 1) + 1
        assert max_live <= K
        # and M does not appear: the bound is M-independent by construction
