"""Incident plane tests (monitor/incidents.py): the always-on flight
recorder's bounds, the multi-window SLO burn-rate alerter, the
cross-plane correlation pass, exactly one schema-valid bundle per
verdict source (stall, storm, straggler, leak, replica_kill, slo_burn),
zero bundles on a quiet run, the ``GET /incidents`` surface, and the
Perfetto timeline export.

The acceptance scenario: an injected recompile storm during a
deadline-missing serving workload produces exactly one bundle whose
correlation section links the SLO-missed requests to the storm's
compile-miss events, and ``ds_trace_export.py`` renders the same run as
valid Chrome trace-event JSON."""

import importlib.util
import json
import os
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.fleet import FleetRouter
from deepspeed_tpu.inference.serving import ServingEngine
from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                              TransformerConfig)
from deepspeed_tpu.monitor.aggregate import ClusterAggregator
from deepspeed_tpu.monitor.incidents import (DEFAULT_BURN_WINDOWS,
                                             EventRingBuffer,
                                             INCIDENT_EVENTS,
                                             INCIDENT_TRIGGERS,
                                             IncidentManager,
                                             SloBurnAlerter, correlate)
from deepspeed_tpu.monitor.telemetry import StepStallWatchdog, Telemetry
from deepspeed_tpu.runtime.config import (TelemetryConfig,
                                          TelemetryIncidentsConfig)

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture(scope="module")
def tiny():
    cfg = TransformerConfig.tiny(hidden_size=64, n_heads=4, n_kv_heads=2)
    model = CausalTransformerLM(cfg)
    params = model.init(jax.random.key(0))
    return cfg, model, params


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def tick(self, dt=1.0):
        self.t += dt


def _prompts(cfg, seed, lengths):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, cfg.vocab_size, (n,)).tolist() for n in lengths]


def _load_script(name):
    path = os.path.join(REPO, "scripts", name + ".py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def checker():
    return _load_script("check_telemetry_schema")


@pytest.fixture(scope="module")
def exporter_mod():
    return _load_script("ds_trace_export")


def _tel(tmp_path, job="inc", incidents=None, **extra):
    inc = {"enabled": True, "cooldown_s": 0.0}
    inc.update(incidents or {})
    raw = {"enabled": True, "output_path": str(tmp_path), "job_name": job,
           "profiling": {"enabled": True, "storm_threshold": 3,
                         "storm_window_s": 60.0},
           "incidents": inc}
    raw.update(extra)
    return Telemetry().configure(TelemetryConfig(raw), rank=0)


def _bundles(bdir):
    return sorted(os.listdir(bdir)) if os.path.isdir(bdir) else []


def _assert_one_valid_bundle(bdir, checker, kind):
    """The per-trigger contract: exactly one bundle, checker-valid, of
    the expected trigger kind.  Returns the decoded incident.json."""
    dirs = _bundles(bdir)
    assert len(dirs) == 1 and dirs[0].endswith(f"-{kind}")
    problems, n = checker.validate_incidents_path(bdir)
    assert problems == [] and n == 1
    with open(os.path.join(bdir, dirs[0], "incident.json")) as f:
        bundle = json.load(f)
    assert bundle["trigger"]["kind"] == kind
    return bundle


def _events(tmp_path, job):
    path = os.path.join(str(tmp_path), job, "events.jsonl")
    return [json.loads(ln) for ln in open(path) if ln.strip()]


# ----------------------------------------------------------------------
# flight recorder
# ----------------------------------------------------------------------
def test_ring_capacity_bound():
    ring = EventRingBuffer(capacity=4, max_age_s=1e9)
    for i in range(10):
        ring.record({"ts": float(i), "kind": "meta", "name": f"e{i}"})
    assert len(ring) == 4 and ring.recorded == 10
    assert [e["name"] for e in ring.dump(now=9.0)] == ["e6", "e7", "e8",
                                                       "e9"]


def test_ring_age_bound():
    ring = EventRingBuffer(capacity=100, max_age_s=10.0)
    ring.record({"ts": 0.0, "kind": "meta", "name": "stale"})
    ring.record({"ts": 95.0, "kind": "meta", "name": "fresh"})
    assert [e["name"] for e in ring.dump(now=100.0)] == ["fresh"]
    # capacity still holds both; only the dump is age-filtered
    assert len(ring) == 2


# ----------------------------------------------------------------------
# SLO burn-rate alerter
# ----------------------------------------------------------------------
def test_burn_alerter_fires_on_rising_edge_only():
    b = SloBurnAlerter(windows=[(10.0, 0.5)], min_requests=4)
    newly, _ = b.observe(0, 0, now=0.0)      # baseline sample
    assert not newly
    newly, detail = b.observe(1, 5, now=5.0)  # 5/6 missed in-window
    assert newly and b.active
    assert detail[0]["miss_rate"] == pytest.approx(5 / 6, abs=1e-3)
    newly, _ = b.observe(1, 6, now=6.0)       # still burning: no re-fire
    assert not newly and b.active
    # recovery: plenty of attained traffic drops the windowed rate
    newly, _ = b.observe(50, 6, now=8.0)
    assert not newly and not b.active
    # a fresh burn after recovery is a new rising edge
    newly, _ = b.observe(50, 60, now=9.0)
    assert newly


def test_burn_alerter_needs_every_window():
    """Multi-window semantics: a short-window blip alone must not fire —
    the long window has to corroborate."""
    b = SloBurnAlerter(windows=[(2.0, 0.5), (100.0, 0.5)], min_requests=2)
    b.observe(0, 0, now=0.0)
    b.observe(100, 0, now=48.0)    # long window dominated by attained
    newly, detail = b.observe(100, 4, now=51.0)
    by_w = {d["window_s"]: d["miss_rate"] for d in detail}
    assert by_w[2.0] == 1.0                     # short window: burning
    assert by_w[100.0] < 0.5                    # long window: healthy
    assert not newly and not b.active


def test_burn_alerter_min_requests_guard():
    b = SloBurnAlerter(windows=[(10.0, 0.5)], min_requests=8)
    b.observe(0, 0, now=0.0)
    newly, detail = b.observe(0, 3, now=1.0)   # 100% missed, but only 3
    assert not newly and detail[0]["miss_rate"] is None


def test_default_burn_windows():
    b = SloBurnAlerter()
    assert b.windows == tuple(sorted(DEFAULT_BURN_WINDOWS))


# ----------------------------------------------------------------------
# cross-plane correlation
# ----------------------------------------------------------------------
def _miss(ts, rid):
    return {"ts": ts, "kind": "serve", "name": "serve/request/deadline",
            "attrs": {"req_id": rid, "slo": "miss"}}


def test_correlate_links_miss_to_causes():
    events = [
        {"ts": 10.1, "kind": "compile", "name": "compile/miss",
         "site": "f", "count": 2, "cause": "new_shape", "dur_ms": 50.0,
         "step": 3},
        {"ts": 10.2, "kind": "gauge", "name": "mem/serve_step/peak_bytes",
         "value": 1 << 20, "peak": 1 << 20, "step": 3},
        {"ts": 10.3, "kind": "comm", "name": "all_reduce", "bytes": 4096,
         "axis": "dp", "dur_ms": 2.0},
        _miss(10.4, "r1"),
        {"ts": 50.0, "kind": "serve", "name": "serve/request/finish",
         "attrs": {"req_id": "r2", "slo": "ok"}},
    ]
    out = correlate(events, window_s=1.0)
    assert out["window_s"] == 1.0
    (link,) = out["links"]
    assert link["req_id"] == "r1"
    assert link["compile_misses"][0]["cause"] == "new_shape"
    assert link["mem_peak_bytes"][0]["span"] == "serve_step"
    assert link["collectives"][0]["op"] == "all_reduce"
    w10 = next(w for w in out["windows"] if w["window"] == 10)
    assert w10["slo_missed"] == ["r1"] and w10["steps"] == [3]
    # the on-time finish neither links nor counts as missed
    w50 = next(w for w in out["windows"] if w["window"] == 50)
    assert w50["slo_missed"] == []


def test_correlate_joins_across_bucket_edges():
    """Time proximity, not bucket identity: a miss at 11.05 still links
    to a compile miss at 10.95 one bucket earlier."""
    events = [
        {"ts": 10.95, "kind": "compile", "name": "compile/miss",
         "site": "f", "count": 1, "cause": "new_shape"},
        _miss(11.05, "edge"),
    ]
    (link,) = correlate(events, window_s=1.0)["links"]
    assert link["req_id"] == "edge" and link["compile_misses"]


def test_correlate_unlinked_miss_produces_no_link():
    assert correlate([_miss(10.0, "alone")], window_s=1.0)["links"] == []


# ----------------------------------------------------------------------
# trigger vocabulary + cooldown + pruning
# ----------------------------------------------------------------------
def test_unknown_trigger_raises():
    mgr = IncidentManager(Telemetry(), bundle_dir="/nonexistent")
    with pytest.raises(ValueError):
        mgr.trigger("bogus")


def test_trigger_cooldown_dedups_per_kind(tmp_path):
    clk = FakeClock()
    mgr = IncidentManager(Telemetry(), bundle_dir=str(tmp_path / "b"),
                          cooldown_s=60.0, clock=clk)
    assert mgr.trigger("stall") == "inc-0001-stall"
    assert mgr.trigger("stall") is None          # same episode: suppressed
    assert mgr.trigger("storm") == "inc-0002-storm"  # other kinds free
    clk.tick(61.0)
    assert mgr.trigger("stall") == "inc-0003-stall"  # episode over


def test_bundle_pruning(tmp_path):
    mgr = IncidentManager(Telemetry(), bundle_dir=str(tmp_path / "b"),
                          cooldown_s=0.0, max_bundles=2)
    for kind in ("stall", "storm", "leak", "slo_burn"):
        assert mgr.trigger(kind)
    kept = sorted(os.listdir(tmp_path / "b"))
    assert kept == ["inc-0003-leak", "inc-0004-slo_burn"]
    assert len(mgr.written) == 4                 # history outlives pruning


# ----------------------------------------------------------------------
# the six verdict sources, one bundle each
# ----------------------------------------------------------------------
def test_stall_trigger_writes_bundle(tmp_path, checker):
    tel = _tel(tmp_path, job="stall")
    wd = StepStallWatchdog(tel, stall_factor=1.0, min_stall_secs=0.0)
    for s in range(3):
        wd.beat(s)
    import time as _time
    future = _time.monotonic() + 1e6
    assert wd.check(now=future)
    bdir = tel.incidents.bundle_dir
    tel.close()
    bundle = _assert_one_valid_bundle(bdir, checker, "stall")
    assert bundle["trigger"]["source"] == "engine/step"
    assert bundle["trigger"]["step"] == 2
    # the open event itself is in the bundle's ring; written comes after
    evs = _events(tmp_path, "stall")
    names = [e["name"] for e in evs if e["kind"] == "incident"]
    assert names == ["incident/open", "incident/written"]
    assert checker.validate_file(
        os.path.join(str(tmp_path), "stall", "events.jsonl")) == []


def test_storm_trigger_writes_bundle(tmp_path, checker):
    tel = _tel(tmp_path, job="storm")
    # first miss is "cold" and excluded from the storm window: 4 misses
    # with distinct shapes cross threshold 3
    for i in range(4):
        tel.profiling.compiles.note_miss(
            "f", ("f", ((f"s{i}", "f32"),)), 0.01, step=i)
    # the storm stays active: further misses must not re-trigger
    tel.profiling.compiles.note_miss(
        "f", ("f", (("s9", "f32"),)), 0.01, step=9)
    bdir = tel.incidents.bundle_dir
    tel.close()
    bundle = _assert_one_valid_bundle(bdir, checker, "storm")
    assert "misses" in bundle["trigger"]["detail"]


def _write_hb_shard(d, rank, step_ms, steps=4):
    with open(os.path.join(d, f"events.rank{rank}.jsonl"), "w") as f:
        for s in range(1, steps + 1):
            f.write(json.dumps(
                {"ts": 100.0 + s, "kind": "heartbeat",
                 "name": "engine/heartbeat", "step": s,
                 "step_ms": step_ms, "rank": rank}) + "\n")


def test_straggler_trigger_writes_bundle(tmp_path, checker):
    tel = _tel(tmp_path, job="strag")
    d = str(tmp_path / "shards")
    os.makedirs(d)
    _write_hb_shard(d, 0, 10.0)
    _write_hb_shard(d, 1, 50.0)                  # 5x the median: flagged
    agg = ClusterAggregator(d, skew_threshold=2.0, min_refresh_secs=0.0,
                            incidents=tel.incidents)
    snap = agg.snapshot()
    assert snap["straggler"]["rank"] == 1
    agg.refresh(force=True)                      # same verdict: no refire
    bdir = tel.incidents.bundle_dir
    tel.close()
    bundle = _assert_one_valid_bundle(bdir, checker, "straggler")
    assert bundle["trigger"]["source"] == "rank1"


def test_leak_trigger_writes_bundle(tiny, tmp_path, checker):
    cfg, model, params = tiny
    tel = _tel(tmp_path, job="leak")
    eng = ServingEngine(model, params, max_batch=1, page_size=8,
                        max_seq=32, dtype=jnp.float32, telemetry=tel)
    # forced invariant violation: an RNG stream owned by no live slot
    eng._rng["ghost"] = jax.random.key(0)
    leaks = eng.leak_report()
    assert "stray_rng" in leaks
    bdir = tel.incidents.bundle_dir
    tel.close()
    bundle = _assert_one_valid_bundle(bdir, checker, "leak")
    assert "stray_rng" in bundle["trigger"]["detail"]


def test_replica_kill_trigger_writes_bundle(tiny, tmp_path, checker):
    cfg, model, params = tiny

    def factory(replica_id, epoch):
        return ServingEngine(model, params, max_batch=4, page_size=8,
                             max_seq=128, dtype=jnp.float32,
                             replica_epoch=epoch)

    tel = _tel(tmp_path, job="kill")
    fleet = FleetRouter(factory, fleet={"replicas": 2, "max_replicas": 2},
                        telemetry=tel)
    (p,) = _prompts(cfg, 5, [8])
    fleet.submit("r0", p, max_new_tokens=2)
    fleet.kill_replica(next(iter(fleet.replicas)), detail="chaos drill")
    fleet.join()
    bdir = tel.incidents.bundle_dir
    tel.close()
    bundle = _assert_one_valid_bundle(bdir, checker, "replica_kill")
    assert "chaos drill" in bundle["trigger"]["detail"]
    # the fleet health context provider rode into the bundle
    assert bundle["context"]["fleet_health"]["n_replicas"] == 2


def test_slo_burn_trigger_writes_bundle(tmp_path, checker):
    tel = _tel(tmp_path, job="burn",
               incidents={"burn_windows": [[60.0, 0.3]],
                          "burn_min_requests": 4})
    tel.incidents.observe_slo(now=0.0)           # baseline reading
    tel.count("serve/slo_missed", 5)
    assert tel.incidents.observe_slo(now=1.0)
    assert not tel.incidents.observe_slo(now=2.0)  # still burning: once
    bdir = tel.incidents.bundle_dir
    tel.close()
    bundle = _assert_one_valid_bundle(bdir, checker, "slo_burn")
    assert bundle["trigger"]["source"] == "serve/slo"


def test_quiet_run_writes_no_bundles(tiny, tmp_path):
    """A healthy serving run with the incident plane armed produces zero
    bundles: no stall, no storm, no leak, no SLO pressure."""
    cfg, model, params = tiny
    tel = _tel(tmp_path, job="quiet")
    eng = ServingEngine(model, params, max_batch=2, page_size=8,
                        max_seq=32, dtype=jnp.float32, telemetry=tel)
    for i, p in enumerate(_prompts(cfg, 7, [4, 5])):
        eng.add_request(i, p, max_new_tokens=2)
    while eng.queue or eng.n_active:
        eng.step()
    assert eng.leak_report() == {}
    bdir = tel.incidents.bundle_dir
    tel.close()
    assert _bundles(bdir) == []


# ----------------------------------------------------------------------
# wiring: ring on every rank, config gating, /incidents endpoint
# ----------------------------------------------------------------------
def test_ring_records_on_sink_gated_ranks(tmp_path):
    """The JSONL sink is rank-0-gated in single-stream mode; the flight
    recorder must not be — rank 1's last seconds matter most in a
    cross-rank incident."""
    tel = _tel(tmp_path, job="r1")
    # emulate a nonzero rank: no sink, incidents still armed
    tel.sink, tel.rank = None, 1
    tel.emit("meta", "rank1/event")
    assert len(tel.incidents.ring) == 1
    tel.close()


def test_incidents_config_gating(tmp_path):
    tel = Telemetry().configure(TelemetryConfig(
        {"enabled": True, "output_path": str(tmp_path),
         "job_name": "off"}), rank=0)
    assert tel.incidents is None                 # default: plane off
    tel.close()
    cfg = TelemetryIncidentsConfig({"enabled": True, "ring_capacity": 7})
    assert cfg.ring_capacity == 7
    for bad in ({"ring_capacity": 0}, {"ring_max_age_s": 0},
                {"burn_min_requests": 0}, {"cooldown_s": -1},
                {"max_bundles": 0}, {"burn_windows": [[0, 0.5]]},
                {"burn_windows": [[60.0, 1.5]]},
                {"burn_windows": [60.0]}):
        with pytest.raises(ValueError):
            TelemetryIncidentsConfig(bad)


def test_incidents_endpoint(tmp_path):
    # exporter without an incident manager: typed 404
    tel = Telemetry().configure(TelemetryConfig(
        {"enabled": True, "output_path": str(tmp_path), "job_name": "no",
         "export": {"enabled": True, "port": 0}}), rank=0)
    try:
        host, port = tel.exporter.address
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"http://{host}:{port}/incidents")
        assert ei.value.code == 404
    finally:
        tel.close()

    tel = _tel(tmp_path, job="yes",
               **{"export": {"enabled": True, "port": 0}})
    try:
        host, port = tel.exporter.address
        tel.incidents.trigger("stall", source="t", detail="d")
        with urllib.request.urlopen(
                f"http://{host}:{port}/incidents") as r:
            snap = json.loads(r.read())
        assert snap["ring"]["capacity"] == 2048
        (inc,) = snap["incidents"]
        assert inc["trigger"] == "stall" and inc["id"].endswith("-stall")
    finally:
        tel.close()


# ----------------------------------------------------------------------
# acceptance: storm during a deadline workload -> one correlated bundle,
# and the run exports as a valid Perfetto timeline
# ----------------------------------------------------------------------
def test_e2e_storm_during_deadline_workload(tiny, tmp_path, checker,
                                            exporter_mod):
    cfg, model, params = tiny
    clk = FakeClock()
    # default burn_min_requests (8) > the 2 deadline requests here, so
    # the burn alerter cannot double-fire: the storm is the ONLY trigger
    tel = _tel(tmp_path, job="e2e", incidents={"cooldown_s": 60.0})
    eng = ServingEngine(model, params, max_batch=1, page_size=8,
                        max_seq=32, dtype=jnp.float32, clock=clk,
                        telemetry=tel)
    pa, pb = _prompts(cfg, 11, [4, 5])
    eng.add_request("miss-a", pa, max_new_tokens=8, deadline_s=2.0)
    eng.add_request("miss-b", pb, max_new_tokens=8, deadline_s=2.0)
    while eng.queue or eng.n_active:
        clk.tick(1.0)
        eng.step()
    assert eng.stats["slo_missed"] == 2
    assert eng.leak_report() == {}               # misses are not leaks
    # the recompile storm lands while the misses are still in the ring
    for i in range(4):
        tel.profiling.compiles.note_miss(
            "serve/decode", ("f", ((f"s{i}", "f32"),)), 0.02, step=i)
    tel.gauge("serve/queue_depth", 0.0)          # a counter for the trace
    bdir = tel.incidents.bundle_dir
    tel.close()

    bundle = _assert_one_valid_bundle(bdir, checker, "storm")
    # correlation: every SLO-missed request links to the storm's
    # compile-miss events in its step window
    linked = {l["req_id"] for l in bundle["correlation"]["links"]}
    assert linked == {"miss-a", "miss-b"}
    for link in bundle["correlation"]["links"]:
        assert any(m["site"] == "serve/decode"
                   for m in link["compile_misses"])
    missed_windows = [w for w in bundle["correlation"]["windows"]
                      if w["slo_missed"]]
    assert missed_windows and all(w["compile_misses"]
                                  for w in missed_windows)
    # the serving context providers rode into the bundle
    assert bundle["context"]["serving_health"]["queue_depth"] == 0
    assert bundle["context"]["inflight_traces"] == []

    # the same run exports as a valid Chrome trace
    out = str(tmp_path / "trace.json")
    rc = exporter_mod.main([os.path.join(str(tmp_path), "e2e"),
                            "-o", out, "--check"])
    assert rc == 0
    obj = json.load(open(out))
    assert exporter_mod.validate_trace(obj) == []
    phases = {e["ph"] for e in obj["traceEvents"]}
    assert {"X", "b", "e", "C", "i", "M"} <= phases
    # both requests render as async begin/end pairs
    ids = {e["id"] for e in obj["traceEvents"] if e["ph"] == "b"}
    assert ids == {"miss-a", "miss-b"}


# ----------------------------------------------------------------------
# timeline export unit coverage
# ----------------------------------------------------------------------
def test_trace_export_span_and_flow_shapes(tmp_path, exporter_mod):
    d = str(tmp_path)
    with open(os.path.join(d, "events.jsonl"), "w") as f:
        f.write(json.dumps({"ts": 100.5, "kind": "span", "name": "step",
                            "dur_ms": 400.0, "step": 1}) + "\n")
        f.write(json.dumps({"ts": 100.6, "kind": "gauge",
                            "name": "mem/step/peak_bytes", "value": 42,
                            "peak": 42}) + "\n")
    for rank, skew in ((0, 0.0), (1, 0.03)):
        with open(os.path.join(d, f"events.rank{rank}.jsonl"), "w") as f:
            f.write(json.dumps({"ts": 100.2 + skew, "kind": "comm",
                                "name": "all_reduce", "bytes": 1024,
                                "axis": "dp", "dur_ms": 5.0,
                                "rank": rank}) + "\n")
    obj = exporter_mod.convert(exporter_mod.load_events(d))
    assert exporter_mod.validate_trace(obj) == []
    evs = obj["traceEvents"]
    # span: ts is stamped at END, so the slice starts dur earlier
    (span,) = [e for e in evs if e["ph"] == "X" and e["cat"] == "span"]
    assert span["dur"] == pytest.approx(400e3)
    assert span["ts"] == 0.0      # earliest slice start is the origin
    comms = [e for e in evs if e["ph"] == "X" and e["cat"] == "comm"]
    assert {c["pid"] for c in comms} == {0, 1}
    # comm slice: ts stamped at END, start = ts - dur, relative to t0
    assert min(c["ts"] for c in comms) == pytest.approx(
        (100.2 - 5e-3 - 100.1) * 1e6, abs=1.0)
    # the two ranks' k=0 all_reduce joins into one flow, earliest first
    flows = sorted([e for e in evs if e.get("cat") == "comm-flow"],
                   key=lambda e: e["ts"])
    assert [f["ph"] for f in flows] == ["s", "f"]
    assert flows[0]["pid"] == 0 and flows[1]["pid"] == 1
    assert flows[0]["id"] == flows[1]["id"] == "all_reduce:0"
    (counter,) = [e for e in evs if e["ph"] == "C"]
    assert counter["args"] == {"value": 42}


def test_trace_export_async_lifecycle(tmp_path, exporter_mod):
    d = str(tmp_path)
    rows = [
        ("serve/request/admitted", 100.0), ("serve/request/prefill_start",
                                            100.1),
        ("serve/request/first_token", 100.2), ("serve/request/finish",
                                               100.5),
    ]
    with open(os.path.join(d, "events.jsonl"), "w") as f:
        for name, ts in rows:
            f.write(json.dumps({"ts": ts, "kind": "serve", "name": name,
                                "attrs": {"req_id": "q"}}) + "\n")
    obj = exporter_mod.convert(exporter_mod.load_events(d))
    assert exporter_mod.validate_trace(obj) == []
    phases = [e["ph"] for e in obj["traceEvents"]
              if e.get("cat") == "request"]
    assert phases == ["b", "n", "n", "e"]


def test_validate_trace_rejects_malformed(exporter_mod):
    v = exporter_mod.validate_trace
    assert v({"traceEvents": [{"ph": "Z", "pid": 0}]})
    assert v({"traceEvents": [{"ph": "X", "pid": 0, "name": "x",
                               "ts": 1.0, "dur": -5.0}]})
    assert v({"traceEvents": [{"ph": "e", "pid": 0, "name": "r",
                               "cat": "request", "id": "q", "ts": 1.0}]})
    assert v({"traceEvents": "nope"}) and v(None)
    assert v({"traceEvents": []}) == []


def test_trace_export_cli(tmp_path, exporter_mod):
    assert exporter_mod.main([str(tmp_path / "missing")]) == 1
    empty = tmp_path / "empty"
    empty.mkdir()
    assert exporter_mod.main([str(empty)]) == 1
    d = tmp_path / "run"
    d.mkdir()
    with open(d / "events.jsonl", "w") as f:
        f.write(json.dumps({"ts": 1.0, "kind": "meta",
                            "name": "run/start"}) + "\n")
    out = str(tmp_path / "t.json")
    assert exporter_mod.main([str(d), "-o", out, "--check"]) == 0
    assert json.load(open(out))["displayTimeUnit"] == "ms"
