"""Fused ragged paged-attention kernel vs the jnp gather oracle.

The Pallas kernel (``ops/pallas/ragged_paged_attention.py``) must be
bit-class equivalent (per-dtype tolerance) to ``paged_decode_attention``'s
jnp path on every ragged mix — decode-only, prefill-only, mixed — through
real ``PagedAllocator`` block tables including prefix-cache shared pages
and partial last pages.  On top of the kernel-level equivalence, the
serving engine's token streams must be BIT-IDENTICAL across
``attention_backend="jnp"`` and ``"pallas-interpret"`` — the backend is a
performance knob, never a quality knob.  All kernel runs use
``interpret=True`` (this suite is CPU tier-1)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.paged_attention import (PagedAllocator, PagedKVCache,
                                               paged_decode_attention,
                                               resolve_attention_backend)
from deepspeed_tpu.ops.pallas.ragged_paged_attention import (
    ragged_paged_attention, ragged_paged_attention_rect)

H, HKV, D, PAGE = 4, 2, 8, 4
NPAGES = 64
TOL = dict(rtol=2e-5, atol=2e-5)


def _build_state(ctx_lens, shared_pages=0, seed=0):
    """A page pool + allocator-produced block tables for one ragged batch.

    ``shared_pages`` > 0 attaches that many leading pages of a holder
    sequence to EVERY request (``allocate(shared=...)`` — the prefix-cache
    admission path), so the kernel must read refcounted shared pages in
    place."""
    rng = np.random.default_rng(seed)
    alloc = PagedAllocator(NPAGES, PAGE, max_pages_per_seq=8,
                           reserve_scratch=True)
    shared = []
    if shared_pages:
        shared = alloc.allocate("__prefix__",
                                shared_pages * PAGE)[:shared_pages]
    for s, c in enumerate(ctx_lens):
        # a request can share at most its own FULL pages
        n_shared = min(shared_pages, max(0, (c - 1) // PAGE))
        alloc.allocate(s, c, shared=shared[:n_shared])
    tables = jnp.asarray(alloc.block_table(list(range(len(ctx_lens)))))
    kp = jnp.asarray(rng.standard_normal((NPAGES, HKV, PAGE, D)),
                     jnp.float32)
    vp = jnp.asarray(rng.standard_normal((NPAGES, HKV, PAGE, D)),
                     jnp.float32)
    return alloc, tables, kp, vp


def _ref(q_packed, q_lens, ctx_lens, kp, vp, tables):
    """Oracle: one rectangular jnp gather call per sequence."""
    cache = PagedKVCache(kp, vp)
    outs, off = [], 0
    for s, (ql, c) in enumerate(zip(q_lens, ctx_lens)):
        o = paged_decode_attention(
            q_packed[off:off + ql][None], cache, tables[s:s + 1],
            jnp.asarray([c], jnp.int32), impl="jnp")
        outs.append(o[0])
        off += ql
    return jnp.concatenate(outs, axis=0)


CASES = [
    ("decode_only", [1, 1, 1], [9, 4, 16]),
    ("prefill_only", [9, 5], [9, 5]),
    ("mixed", [6, 1, 3, 1], [6, 13, 7, 16]),
    ("length_one", [1], [1]),
    ("page_boundary", [4, 1], [8, 8]),       # ctx exactly fills pages
    ("partial_last_page", [5, 1], [5, 10]),  # ctx ends mid-page
]


@pytest.mark.parametrize("name,q_lens,ctx_lens",
                         CASES, ids=[c[0] for c in CASES])
def test_matches_jnp_oracle(name, q_lens, ctx_lens):
    _, tables, kp, vp = _build_state(ctx_lens)
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((sum(q_lens), H, D)), jnp.float32)
    got = ragged_paged_attention(q, kp, vp, tables,
                                 jnp.asarray(ctx_lens, jnp.int32), q_lens,
                                 interpret=True)
    want = _ref(q, q_lens, ctx_lens, kp, vp, tables)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), **TOL)


def test_prefix_cache_shared_pages_read_in_place():
    """Both requests' tables lead with the SAME physical pages (refcounted
    prefix attach); the kernel must produce the oracle's answer reading
    them in place — and the mix has a decode rider over the same pool."""
    q_lens, ctx_lens = [5, 1, 1], [13, 11, 9]
    alloc, tables, kp, vp = _build_state(ctx_lens, shared_pages=2)
    t = np.asarray(tables)
    assert t[0, 0] == t[1, 0] and t[0, 1] == t[1, 1]   # genuinely shared
    assert alloc.audit() == {}
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.standard_normal((sum(q_lens), H, D)), jnp.float32)
    got = ragged_paged_attention(q, kp, vp, tables,
                                 jnp.asarray(ctx_lens, jnp.int32), q_lens,
                                 interpret=True)
    want = _ref(q, q_lens, ctx_lens, kp, vp, tables)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), **TOL)


@pytest.mark.parametrize("T", [1, 5, 8, 12])
def test_rect_front_end(T):
    """The rectangular wrapper (the jitted serving path's shape) must
    match the oracle for decode (T=1), in-tile prefill, exact-tile, and
    the Tp-padding path (T=12 > q_tile=8)."""
    B = 3
    ctx = [T + 3, T, T + 9]
    _, tables, kp, vp = _build_state(ctx)
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    lengths = jnp.asarray(ctx, jnp.int32)
    got = ragged_paged_attention_rect(q, kp, vp, tables, lengths,
                                     interpret=True)
    want = paged_decode_attention(q, PagedKVCache(kp, vp), tables, lengths,
                                  impl="jnp")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), **TOL)


def test_backend_selected_entry_point():
    """``paged_decode_attention(backend=...)`` routes "pallas-interpret"
    through the ragged kernel and agrees with the jnp backend."""
    ctx = [7, 12]
    _, tables, kp, vp = _build_state(ctx)
    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.standard_normal((2, 1, H, D)), jnp.float32)
    cache = PagedKVCache(kp, vp)
    lengths = jnp.asarray(ctx, jnp.int32)
    a = paged_decode_attention(q, cache, tables, lengths, backend="jnp")
    b = paged_decode_attention(q, cache, tables, lengths,
                               backend="pallas-interpret")
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), **TOL)


def test_resolve_attention_backend():
    assert resolve_attention_backend(None) == (None, False)
    assert resolve_attention_backend("auto") == (None, False)
    assert resolve_attention_backend("jnp") == ("jnp", False)
    assert resolve_attention_backend("pallas") == ("pallas", False)
    assert resolve_attention_backend("pallas-interpret") == ("pallas", True)
    with pytest.raises(ValueError):
        resolve_attention_backend("cuda")


def test_deprecated_shim_still_serves():
    """``paged_attention_pallas`` (old decode-only surface) delegates to
    the ragged kernel with unchanged semantics."""
    from deepspeed_tpu.ops.pallas.decode_attention import \
        paged_attention_pallas
    ctx = [9, 14]
    _, tables, kp, vp = _build_state(ctx)
    rng = np.random.default_rng(5)
    q = jnp.asarray(rng.standard_normal((2, 1, H, D)), jnp.float32)
    lengths = jnp.asarray(ctx, jnp.int32)
    got = paged_attention_pallas(q, kp, vp, tables, lengths, interpret=True)
    want = paged_decode_attention(q, PagedKVCache(kp, vp), tables, lengths,
                                  impl="jnp")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), **TOL)


# -- serving end-to-end ----------------------------------------------------

@pytest.fixture(scope="module")
def tiny():
    from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                                  TransformerConfig)
    cfg = TransformerConfig.tiny(hidden_size=64, n_heads=4, n_kv_heads=2)
    model = CausalTransformerLM(cfg)
    params = model.init(jax.random.key(0))
    return cfg, model, params


def test_serving_bit_identical_across_backends(tiny):
    """The whole engine — bucketed prefill, batched decode, sampling —
    must emit bit-identical token streams under the jnp gather path and
    the interpret-mode ragged kernel, with a clean leak report."""
    from deepspeed_tpu.inference.serving import ServingEngine
    cfg, model, params = tiny
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, (n,)).tolist()
               for n in (5, 9, 3)]

    def run(backend):
        eng = ServingEngine(model, params, max_batch=4, page_size=8,
                            max_seq=64, dtype=jnp.float32,
                            serving={"attention_backend": backend})
        assert eng.attention_backend == backend
        out = eng.generate(prompts, max_new_tokens=4)
        assert eng.leak_report() == {}
        return out

    assert run("jnp") == run("pallas-interpret")


def test_bad_backend_rejected_at_construction(tiny):
    from deepspeed_tpu.inference.serving import ServingEngine
    cfg, model, params = tiny
    with pytest.raises(ValueError, match="attention_backend"):
        ServingEngine(model, params, max_batch=1, page_size=8, max_seq=64,
                      dtype=jnp.float32,
                      serving={"attention_backend": "cuda"})


def test_reservation_trimmed_and_audited(tiny):
    """Admission must trim the bucketed-prefill over-allocation to the
    request's true page need (``_trim_reservation``), and
    ``leak_report()`` must flag any active slot whose reservation drifts
    from it."""
    from deepspeed_tpu.inference.serving import ServingEngine
    cfg, model, params = tiny
    eng = ServingEngine(model, params, max_batch=1, page_size=4,
                        max_seq=32, dtype=jnp.float32)
    # prompt 9 + budget 2 = 11 tokens -> 3 pages; the prefill bucket pads
    # to 16 tokens -> 4 pages reserved, so admission MUST return one
    prompt = list(range(1, 10))
    eng.add_request("r0", prompt, max_new_tokens=2)
    eng.step()
    assert eng.slots[0] is not None and eng.slots[0].req_id == "r0"
    assert len(eng.alloc.seq_pages["r0"]) == 3
    assert eng.leak_report() == {}
    # force a drifted reservation: the audit must name the slot
    eng.alloc.extend("r0", 16)
    leaks = eng.leak_report()
    assert "over_reserved_slots" in leaks
    assert leaks["over_reserved_slots"]["r0"]["held"] == 4
    eng.alloc.shrink("r0", 11)
    assert eng.leak_report() == {}
