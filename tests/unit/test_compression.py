"""Compression library tests.

Parity model: reference ``tests/unit/compression/test_compression.py``
(LinearLayer_Compress quant/prune behaviour, init_compression config
parsing, redundancy_clean).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.compression import (CompressionConfig, init_compression,
                                       redundancy_clean)
from deepspeed_tpu.compression import transforms as T
from unit.simple_model import SimpleModel, base_config, random_batch

HIDDEN = 16


# ----------------------------------------------------------------------
# primitive transforms
# ----------------------------------------------------------------------
def test_quantize_weight_levels_and_ste():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(32, 32)), jnp.float32)
    q = T.quantize_weight(w, bits=4, groups=4)
    # 4-bit symmetric → at most 16 distinct values per group
    per_group = np.asarray(q).reshape(4, -1)
    for g in per_group:
        assert len(np.unique(np.round(g, 6))) <= 16
    # STE: gradient of sum(q(w)) w.r.t. w is all-ones (identity backward)
    grad = jax.grad(lambda w: jnp.sum(T.quantize_weight(w, bits=4)))(w)
    np.testing.assert_allclose(np.asarray(grad), 1.0)


def test_quantize_asymmetric_preserves_range():
    w = jnp.asarray(np.linspace(0.0, 1.0, 64), jnp.float32)
    q = np.asarray(T.quantize_weight(w, bits=8, symmetric=False))
    assert abs(q.min() - 0.0) < 1e-2 and abs(q.max() - 1.0) < 1e-2


def test_sparse_prune_ratio():
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.normal(size=(64, 64)), jnp.float32)
    out = np.asarray(T.sparse_prune(w, dense_ratio=0.25))
    nnz = np.count_nonzero(out)
    assert abs(nnz / out.size - 0.25) < 0.01
    # survivors are the largest-magnitude entries
    thresh = np.quantile(np.abs(np.asarray(w)), 0.75)
    assert np.all(np.abs(out[out != 0]) >= thresh - 1e-6)


def test_row_and_head_prune_structured():
    rng = np.random.default_rng(2)
    w = jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)
    out = np.asarray(T.row_prune(w, dense_ratio=0.5, axis=-1))
    col_nnz = np.count_nonzero(np.abs(out).sum(axis=0))
    assert col_nnz == 4
    w2 = jnp.asarray(rng.normal(size=(4 * 4, 8)), jnp.float32)  # H=4, dh=4
    out2 = np.asarray(T.head_prune(w2, num_heads=4, dense_ratio=0.5))
    blocks = out2.reshape(4, 4, 8)
    alive = [i for i in range(4) if np.abs(blocks[i]).sum() > 0]
    assert len(alive) == 2


def test_activation_quantization():
    x = jnp.asarray(np.linspace(-2, 2, 100), jnp.float32)
    q = np.asarray(T.quantize_activation(x, bits=8))
    assert np.max(np.abs(q - np.asarray(x))) < 0.05


# ----------------------------------------------------------------------
# config → spec → transform
# ----------------------------------------------------------------------
CFG = {
    "weight_quantization": {
        "shared_parameters": {"enabled": True, "quantize_groups": 1,
                              "quantization_type": "symmetric",
                              "schedule_offset": 2},
        "different_groups": {
            "wq1": {"params": {"start_bits": 8, "target_bits": 8},
                    "modules": ["layer_0"]}},
    },
    "sparse_pruning": {
        "shared_parameters": {"enabled": True, "method": "l1",
                              "schedule_offset": 0},
        "different_groups": {
            "sp1": {"params": {"dense_ratio": 0.5},
                    "modules": ["layer_1"]}},
    },
}


def test_config_parsing():
    cc = CompressionConfig(CFG)
    assert cc.enabled and len(cc.groups) == 2
    methods = {g.method for g in cc.groups}
    assert methods == {"weight_quantization", "sparse_pruning"}


def test_spec_schedule_gating():
    spec = init_compression(None, {"compression_training": CFG})
    params = {"layer_0": {"w": jnp.asarray(
        np.random.default_rng(0).normal(size=(8, 8)), jnp.float32)},
        "layer_1": {"w": jnp.asarray(
            np.random.default_rng(1).normal(size=(8, 8)), jnp.float32)}}
    # step 0: quant group (offset 2) inactive, sparse group (offset 0) active
    out0 = spec.transform(params, 0)
    np.testing.assert_array_equal(np.asarray(out0["layer_0"]["w"]),
                                  np.asarray(params["layer_0"]["w"]))
    assert np.count_nonzero(np.asarray(out0["layer_1"]["w"])) == 32
    # step 5: both active
    out5 = spec.transform(params, 5)
    assert not np.array_equal(np.asarray(out5["layer_0"]["w"]),
                              np.asarray(params["layer_0"]["w"]))


def test_redundancy_clean_layer_reduction():
    cfg = {"compression_training": {
        "layer_reduction": {"enabled": True, "keep_number_layer": 2,
                            "teacher_layer": [0, 2]}}}
    params = {"layers": {"w": np.arange(4 * 3, dtype=np.float32).reshape(4, 3)},
              "final_norm": np.ones(3, np.float32)}
    out = redundancy_clean(params, cfg)
    assert out["layers"]["w"].shape == (2, 3)
    np.testing.assert_array_equal(out["layers"]["w"][1],
                                  params["layers"]["w"][2])


# ----------------------------------------------------------------------
# engine integration
# ----------------------------------------------------------------------
def test_engine_compressed_training():
    model = SimpleModel(hidden_dim=HIDDEN)
    params = model.init(jax.random.key(0))
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config=base_config(compression_training={
            "weight_quantization": {
                # in-forward STE path (reference semantics: in_forward=False
                # routes weight quantization to the step-time MoQ quantizer
                # instead — covered by tests/unit/test_moq.py)
                "shared_parameters": {"enabled": True,
                                      "quantize_weight_in_forward": True,
                                      "schedule_offset": 1},
                "different_groups": {
                    "all": {"params": {"target_bits": 8},
                            "modules": ["*"]}}}}))
    assert engine._compression is not None
    losses = [float(engine.train_batch(batch=random_batch(8, HIDDEN, seed=0)))
              for _ in range(6)]
    assert losses[-1] < losses[0]  # trains through the phase flip


# ----------------------------------------------------------------------
# mesh-aware structured pruning (reference Column/RowParallelLinear_Compress,
# compression/basic_layer.py:836,879 — each tp rank prunes dense_ratio of
# its OWN slice, so shards stay balanced)
# ----------------------------------------------------------------------
def test_block_topk_mask_balanced_per_shard():
    rng = np.random.default_rng(0)
    scores = jnp.asarray(rng.normal(size=(16,)) ** 2)
    # global top-k may unbalance; per-block keeps 4 of 8 in EACH half
    mask = np.asarray(T._topk_mask(scores, 0.5, num_blocks=2))
    assert mask.sum() == 8
    assert mask[:8].sum() == 4 and mask[8:].sum() == 4


def test_head_prune_tp_balanced():
    H, dh, d = 8, 4, 16
    rng = np.random.default_rng(1)
    w = rng.normal(size=(H * dh, d)).astype(np.float32)
    # make the 4 largest-magnitude heads all live in the FIRST tp half
    w[: 4 * dh] *= 10.0
    pruned_global = np.asarray(T.head_prune(jnp.asarray(w), H, 0.5))
    pruned_tp = np.asarray(T.head_prune(jnp.asarray(w), H, 0.5, tp_degree=2))

    def live_heads(p):
        return [int(np.abs(p[i * dh:(i + 1) * dh]).sum() > 0)
                for i in range(H)]
    lg, lt = live_heads(pruned_global), live_heads(pruned_tp)
    assert sum(lg) == 4 and sum(lg[:4]) == 4      # global: all on shard 0
    assert sum(lt) == 4 and sum(lt[:4]) == 2 and sum(lt[4:]) == 2  # balanced


def test_compression_spec_consumes_tp_rules():
    from jax.sharding import PartitionSpec as P
    from deepspeed_tpu.compression.compress import CompressionSpec
    from deepspeed_tpu.parallel import groups
    from deepspeed_tpu.parallel.topology import TP_AXIS, TopologyConfig

    groups.reset_mesh()
    mesh = groups.initialize_mesh(TopologyConfig(tp=2, fsdp=4))
    try:
        cfg = CompressionConfig({
            "head_pruning": {
                "shared_parameters": {"enabled": True, "num_heads": 8,
                                      "schedule_offset": 0},
                "different_groups": {
                    "att": {"params": {"dense_ratio": 0.5},
                            "modules": ["wo"]}}}})
        spec = CompressionSpec(cfg, num_heads=8,
                               tp_rules=[(r"wo", P(TP_AXIS, None))],
                               mesh=mesh)
        H, dh, d = 8, 4, 16
        rng = np.random.default_rng(2)
        w = rng.normal(size=(H * dh, d)).astype(np.float32)
        w[: 4 * dh] *= 10.0     # biggest heads all in shard 0
        out = spec.transform({"wo": jnp.asarray(w)}, step=1)
        p = np.asarray(out["wo"])
        live = [int(np.abs(p[i * dh:(i + 1) * dh]).sum() > 0)
                for i in range(H)]
        # tp=2 over the H*dh axis → 2 heads survive in each shard half
        assert sum(live[:4]) == 2 and sum(live[4:]) == 2, live
        # unsharded leaf (no rule match) keeps global ranking
        out2 = spec.transform({"other": jnp.asarray(w)}, step=1)
        assert np.abs(np.asarray(out2["other"])).sum() > 0
    finally:
        groups.reset_mesh()


def test_engine_mesh_aware_head_pruning_trains():
    """End-to-end on a tp=2 mesh: the engine passes its tp rule table into
    the compression spec and compressed training still descends."""
    from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                                  TransformerConfig)
    from deepspeed_tpu.parallel import groups
    groups.reset_mesh()
    cfg = TransformerConfig.tiny(hidden_size=32, n_heads=4, vocab_size=128)
    model = CausalTransformerLM(cfg)
    params = model.init(jax.random.key(0))
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": 4,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "mesh": {"tp": 2, "fsdp": 4},
            "zero_optimization": {"stage": 2},
            "compression_training": {
                "head_pruning": {
                    "shared_parameters": {"enabled": True,
                                          "num_heads": cfg.n_heads,
                                          "schedule_offset": 2},
                    "different_groups": {
                        "att": {"params": {"dense_ratio": 0.5},
                                "modules": ["wo"]}}}},
        })
    assert engine._compression is not None
    assert engine._compression.tp_rules, "engine must pass tp rules"
    rng = np.random.default_rng(0)
    batch = {"input_ids": rng.integers(0, 128, (4, 32)).astype(np.int32)}
    losses = [float(engine.train_batch(batch=batch)) for _ in range(8)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
    groups.reset_mesh()
