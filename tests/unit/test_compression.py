"""Compression library tests.

Parity model: reference ``tests/unit/compression/test_compression.py``
(LinearLayer_Compress quant/prune behaviour, init_compression config
parsing, redundancy_clean).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.compression import (CompressionConfig, init_compression,
                                       redundancy_clean)
from deepspeed_tpu.compression import transforms as T
from unit.simple_model import SimpleModel, base_config, random_batch

HIDDEN = 16


# ----------------------------------------------------------------------
# primitive transforms
# ----------------------------------------------------------------------
def test_quantize_weight_levels_and_ste():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(32, 32)), jnp.float32)
    q = T.quantize_weight(w, bits=4, groups=4)
    # 4-bit symmetric → at most 16 distinct values per group
    per_group = np.asarray(q).reshape(4, -1)
    for g in per_group:
        assert len(np.unique(np.round(g, 6))) <= 16
    # STE: gradient of sum(q(w)) w.r.t. w is all-ones (identity backward)
    grad = jax.grad(lambda w: jnp.sum(T.quantize_weight(w, bits=4)))(w)
    np.testing.assert_allclose(np.asarray(grad), 1.0)


def test_quantize_asymmetric_preserves_range():
    w = jnp.asarray(np.linspace(0.0, 1.0, 64), jnp.float32)
    q = np.asarray(T.quantize_weight(w, bits=8, symmetric=False))
    assert abs(q.min() - 0.0) < 1e-2 and abs(q.max() - 1.0) < 1e-2


def test_sparse_prune_ratio():
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.normal(size=(64, 64)), jnp.float32)
    out = np.asarray(T.sparse_prune(w, dense_ratio=0.25))
    nnz = np.count_nonzero(out)
    assert abs(nnz / out.size - 0.25) < 0.01
    # survivors are the largest-magnitude entries
    thresh = np.quantile(np.abs(np.asarray(w)), 0.75)
    assert np.all(np.abs(out[out != 0]) >= thresh - 1e-6)


def test_row_and_head_prune_structured():
    rng = np.random.default_rng(2)
    w = jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)
    out = np.asarray(T.row_prune(w, dense_ratio=0.5, axis=-1))
    col_nnz = np.count_nonzero(np.abs(out).sum(axis=0))
    assert col_nnz == 4
    w2 = jnp.asarray(rng.normal(size=(4 * 4, 8)), jnp.float32)  # H=4, dh=4
    out2 = np.asarray(T.head_prune(w2, num_heads=4, dense_ratio=0.5))
    blocks = out2.reshape(4, 4, 8)
    alive = [i for i in range(4) if np.abs(blocks[i]).sum() > 0]
    assert len(alive) == 2


def test_activation_quantization():
    x = jnp.asarray(np.linspace(-2, 2, 100), jnp.float32)
    q = np.asarray(T.quantize_activation(x, bits=8))
    assert np.max(np.abs(q - np.asarray(x))) < 0.05


# ----------------------------------------------------------------------
# config → spec → transform
# ----------------------------------------------------------------------
CFG = {
    "weight_quantization": {
        "shared_parameters": {"enabled": True, "quantize_groups": 1,
                              "quantization_type": "symmetric",
                              "schedule_offset": 2},
        "different_groups": {
            "wq1": {"params": {"start_bits": 8, "target_bits": 8},
                    "modules": ["layer_0"]}},
    },
    "sparse_pruning": {
        "shared_parameters": {"enabled": True, "method": "l1",
                              "schedule_offset": 0},
        "different_groups": {
            "sp1": {"params": {"dense_ratio": 0.5},
                    "modules": ["layer_1"]}},
    },
}


def test_config_parsing():
    cc = CompressionConfig(CFG)
    assert cc.enabled and len(cc.groups) == 2
    methods = {g.method for g in cc.groups}
    assert methods == {"weight_quantization", "sparse_pruning"}


def test_spec_schedule_gating():
    spec = init_compression(None, {"compression_training": CFG})
    params = {"layer_0": {"w": jnp.asarray(
        np.random.default_rng(0).normal(size=(8, 8)), jnp.float32)},
        "layer_1": {"w": jnp.asarray(
            np.random.default_rng(1).normal(size=(8, 8)), jnp.float32)}}
    # step 0: quant group (offset 2) inactive, sparse group (offset 0) active
    out0 = spec.transform(params, 0)
    np.testing.assert_array_equal(np.asarray(out0["layer_0"]["w"]),
                                  np.asarray(params["layer_0"]["w"]))
    assert np.count_nonzero(np.asarray(out0["layer_1"]["w"])) == 32
    # step 5: both active
    out5 = spec.transform(params, 5)
    assert not np.array_equal(np.asarray(out5["layer_0"]["w"]),
                              np.asarray(params["layer_0"]["w"]))


def test_redundancy_clean_layer_reduction():
    cfg = {"compression_training": {
        "layer_reduction": {"enabled": True, "keep_number_layer": 2,
                            "teacher_layer": [0, 2]}}}
    params = {"layers": {"w": np.arange(4 * 3, dtype=np.float32).reshape(4, 3)},
              "final_norm": np.ones(3, np.float32)}
    out = redundancy_clean(params, cfg)
    assert out["layers"]["w"].shape == (2, 3)
    np.testing.assert_array_equal(out["layers"]["w"][1],
                                  params["layers"]["w"][2])


# ----------------------------------------------------------------------
# engine integration
# ----------------------------------------------------------------------
def test_engine_compressed_training():
    model = SimpleModel(hidden_dim=HIDDEN)
    params = model.init(jax.random.key(0))
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config=base_config(compression_training={
            "weight_quantization": {
                # in-forward STE path (reference semantics: in_forward=False
                # routes weight quantization to the step-time MoQ quantizer
                # instead — covered by tests/unit/test_moq.py)
                "shared_parameters": {"enabled": True,
                                      "quantize_weight_in_forward": True,
                                      "schedule_offset": 1},
                "different_groups": {
                    "all": {"params": {"target_bits": 8},
                            "modules": ["*"]}}}}))
    assert engine._compression is not None
    losses = [float(engine.train_batch(batch=random_batch(8, HIDDEN, seed=0)))
              for _ in range(6)]
    assert losses[-1] < losses[0]  # trains through the phase flip
