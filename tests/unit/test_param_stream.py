"""Training-time parameter offload (param-stream) tests.

Parity model: reference ``tests/unit/runtime/zero/test_zero_context*.py``
(``zero.Init(remote_device=...)`` semantics) and the offload paths of
``test_zero.py`` — here the bar is trajectory equality against the
device-resident offload engine, since both share the host C++ Adam.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                              TransformerConfig)
from unit.simple_model import base_config

V, S = 64, 32


def _toy_lm(**kw):
    cfg = TransformerConfig.tiny(vocab_size=V, max_seq_len=S,
                                 hidden_size=32, n_layers=3, n_heads=4,
                                 loss_chunk_size=0, **kw)
    return CausalTransformerLM(cfg)


def _batch(bsz=8, seed=0, gas=None):
    rng = np.random.default_rng(seed)
    if gas:
        return {"input_ids": rng.integers(0, V, size=(gas, bsz, S),
                                          dtype=np.int64)}
    return {"input_ids": rng.integers(0, V, size=(bsz, S), dtype=np.int64)}


def _engine(model, params, **overrides):
    eng, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config=base_config(**overrides))
    return eng


def _stream_cfg(extra_param=None, stage=0, **overrides):
    zo = {"stage": stage,
          "offload_param": dict({"device": "cpu"}, **(extra_param or {})),
          "offload_optimizer": {"device": "cpu"}}
    return dict(zero_optimization=zo, **overrides)


def _offload_cfg(**overrides):
    return dict(zero_optimization={
        "stage": 0, "offload_optimizer": {"device": "cpu"}}, **overrides)


# ----------------------------------------------------------------------
# trajectory equality vs the device-resident offload engine
# ----------------------------------------------------------------------
def test_stream_matches_offload_trajectory():
    """fp32 compute: the streamed step must track the resident offload
    step (same host Adam, same math, different execution shape)."""
    model = _toy_lm()
    params = model.init(jax.random.key(0))
    e_res = _engine(model, params, **_offload_cfg())
    e_str = _engine(model, params, **_stream_cfg())
    assert e_str._param_stream is not None
    assert e_str._param_stream.store.homogeneous
    for seed in range(3):
        b = _batch(seed=seed)
        l1 = float(e_res.train_batch(batch=b))
        l2 = float(e_str.train_batch(batch=b))
        np.testing.assert_allclose(l1, l2, rtol=2e-5)
    p_res = e_res.module_state_dict()
    p_str = e_str._param_stream.params_tree()
    np.testing.assert_allclose(np.asarray(p_str["layers"]["wq"]),
                               np.asarray(p_res["layers"]["wq"]),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(p_str["tok_embed"]),
                               np.asarray(p_res["tok_embed"]),
                               rtol=1e-4, atol=1e-5)


def test_stream_gas_matches_offload():
    model = _toy_lm()
    params = model.init(jax.random.key(0))
    e_res = _engine(model, params, gradient_accumulation_steps=2,
                    **_offload_cfg())
    e_str = _engine(model, params, gradient_accumulation_steps=2,
                    **_stream_cfg())
    for seed in range(2):
        b = _batch(seed=seed, gas=2)
        l1 = float(e_res.train_batch(batch=b))
        l2 = float(e_str.train_batch(batch=b))
        np.testing.assert_allclose(l1, l2, rtol=2e-5)


def test_stream_grad_clipping_matches():
    model = _toy_lm()
    params = model.init(jax.random.key(0))
    e_res = _engine(model, params, gradient_clipping=0.01, **_offload_cfg())
    e_str = _engine(model, params, gradient_clipping=0.01, **_stream_cfg())
    for seed in range(2):
        b = _batch(seed=seed)
        l1 = float(e_res.train_batch(batch=b))
        l2 = float(e_str.train_batch(batch=b))
        np.testing.assert_allclose(l1, l2, rtol=2e-5)
    np.testing.assert_allclose(e_str._last_metrics.grad_norm,
                               e_res._last_metrics.grad_norm, rtol=1e-3)


def test_resident_layers_pinning_matches():
    """Pinned working sets are a pure perf knob — identical trajectory."""
    model = _toy_lm()
    params = model.init(jax.random.key(0))
    e0 = _engine(model, params, **_stream_cfg())
    e2 = _engine(model, params,
                 **_stream_cfg(extra_param={"resident_layers": 2}))
    assert e2._param_stream.resident_layers == 2
    for seed in range(2):
        b = _batch(seed=seed)
        l0 = float(e0.train_batch(batch=b))
        l2 = float(e2.train_batch(batch=b))
        np.testing.assert_allclose(l0, l2, rtol=1e-6)


def test_stream_trains_tied_gpt2_shape():
    """GPT-2 family: tied embeddings + learned positions + biases all ride
    the resident group; loss must fall."""
    model = _toy_lm(activation="gelu", use_rmsnorm=False, use_rope=False,
                    tie_embeddings=True, use_bias=True, norm_bias=True)
    params = model.init(jax.random.key(0))
    e = _engine(model, params, **_stream_cfg())
    losses = [float(e.train_batch(batch=_batch(seed=0))) for _ in range(5)]
    assert losses[-1] < losses[0]


def test_stream_local_window_pattern():
    """Per-layer sliding windows ride as side inputs, like the scan."""
    model = _toy_lm(local_attn_pattern=(0, 8, 0))
    params = model.init(jax.random.key(0))
    e_res = _engine(model, params, **_offload_cfg())
    e_str = _engine(model, params, **_stream_cfg())
    b = _batch(seed=0)
    np.testing.assert_allclose(float(e_res.train_batch(batch=b)),
                               float(e_str.train_batch(batch=b)),
                               rtol=2e-5)


def test_stream_moe_list_layers():
    """Heterogeneous (MoE list) stacks stream per-layer layouts; the MoE
    aux loss flows into the gate gradients."""
    model = _toy_lm(moe_num_experts=4, moe_top_k=1, moe_layer_freq=2)
    params = model.init(jax.random.key(0))
    e_str = _engine(model, params, **_stream_cfg())
    assert not e_str._param_stream.store.homogeneous
    wg_before = e_str._param_stream.params_tree()["layers"][1]["moe"][
        "wg"].copy()
    losses = [float(e_str.train_batch(batch=_batch(seed=s)))
              for s in range(4)]
    assert losses[-1] < losses[0]
    wg_after = e_str._param_stream.params_tree()["layers"][1]["moe"]["wg"]
    assert np.abs(wg_after - wg_before).max() > 0   # gate actually learns


# ----------------------------------------------------------------------
# fp16 overflow + loss-scale automaton
# ----------------------------------------------------------------------
def test_stream_fp16_overflow_skips_step():
    model = _toy_lm()
    params = model.init(jax.random.key(0))
    e = _engine(model, params,
                fp16={"enabled": True, "initial_scale_power": 24,
                      "hysteresis": 1},
                **_stream_cfg())
    before = e._param_stream.params_tree()["layers"]["wq"].copy()
    e.train_batch(batch=_batch(seed=0))
    if int(e.state.skipped_steps) >= 1:
        after = e._param_stream.params_tree()["layers"]["wq"]
        np.testing.assert_array_equal(after, before)
        assert float(e.state.loss_scale.cur_scale) < 2.0 ** 24
    # train until a successful step happens; scale keeps adapting
    for s in range(6):
        e.train_batch(batch=_batch(seed=s))
    assert int(e.state.global_step) == 7
    assert int(e.state.skipped_steps) < 7


# ----------------------------------------------------------------------
# checkpoint / state surface
# ----------------------------------------------------------------------
def test_stream_checkpoint_roundtrip(tmp_path):
    model = _toy_lm()
    params = model.init(jax.random.key(0))
    e1 = _engine(model, params, **_stream_cfg())
    for s in range(2):
        e1.train_batch(batch=_batch(seed=s))
    e1.save_checkpoint(str(tmp_path), tag="ck")
    e2 = _engine(model, params, **_stream_cfg())
    e2.load_checkpoint(str(tmp_path), tag="ck")
    np.testing.assert_allclose(e2._param_stream.store.masters,
                               e1._param_stream.store.masters, rtol=1e-6)
    b = _batch(seed=9)
    np.testing.assert_allclose(float(e1.train_batch(batch=b)),
                               float(e2.train_batch(batch=b)), rtol=1e-5)


def test_stream_nvme_memmap(tmp_path):
    """ZeRO-Infinity: host state memmap-backed under nvme_path."""
    model = _toy_lm()
    params = model.init(jax.random.key(0))
    e_cpu = _engine(model, params, **_stream_cfg())
    e_nvme = _engine(model, params, **_stream_cfg(
        extra_param={"device": "nvme", "nvme_path": str(tmp_path)}))
    assert isinstance(e_nvme._param_stream.store.masters, np.memmap)
    files = os.listdir(os.path.join(
        str(tmp_path), "zero_param_stream", "rank0"))
    assert any("layer_master" in f for f in files)
    for seed in range(2):
        b = _batch(seed=seed)
        np.testing.assert_allclose(float(e_cpu.train_batch(batch=b)),
                                   float(e_nvme.train_batch(batch=b)),
                                   rtol=1e-6)


def test_stream_nvme_via_optimizer_device(tmp_path):
    """offload_param cpu + offload_optimizer nvme memmaps ONLY the Adam
    moments — optimizer NVMe offload is independent of where params live
    (round-4 advisor), and the hot upload mirrors / masters stay in RAM
    as the explicit 'cpu' setting demands."""
    model = _toy_lm()
    params = model.init(jax.random.key(0))
    zo = {"stage": 0,
          "offload_param": {"device": "cpu"},
          "offload_optimizer": {"device": "nvme",
                                "nvme_path": str(tmp_path)}}
    e = _engine(model, params, zero_optimization=zo)
    store = e._param_stream.store
    assert all(isinstance(m, np.memmap) for m in store.moments)
    assert not isinstance(store.masters, np.memmap)
    assert not isinstance(store.mirrors, np.memmap)


def test_stream_nvme_param_with_cpu_optimizer_keeps_moments_in_ram(tmp_path):
    """The reverse split: params on NVMe, moments explicitly in RAM."""
    model = _toy_lm()
    params = model.init(jax.random.key(0))
    e = _engine(model, params, **_stream_cfg(
        extra_param={"device": "nvme", "nvme_path": str(tmp_path)}))
    store = e._param_stream.store
    assert isinstance(store.masters, np.memmap)
    assert not any(isinstance(m, np.memmap) for m in store.moments)


def test_stream_buffer_count_deepens_window():
    """buffer_count sets the on-device working-set window (prefetch depth
    buffer_count-1); a deeper window is a pure perf knob — trajectory
    identical to double buffering."""
    model = _toy_lm()
    params = model.init(jax.random.key(0))
    e2 = _engine(model, params,
                 **_stream_cfg(extra_param={"buffer_count": 2}))
    e4 = _engine(model, params,
                 **_stream_cfg(extra_param={"buffer_count": 4}))
    assert e4._param_stream.buffer_count == 4
    for seed in range(2):
        b = _batch(seed=seed)
        np.testing.assert_allclose(float(e2.train_batch(batch=b)),
                                   float(e4.train_batch(batch=b)),
                                   rtol=1e-6)


def test_host_store_shape_mismatch_not_homogeneous():
    """Equal totals + equal structure but different per-leaf shapes must
    take the heterogeneous path — sharing layer 0's FlatLayout would
    unflatten transposed views (round-4 advisor)."""
    from deepspeed_tpu.runtime.zero.param_stream import HostParamStore
    t0 = {"w": np.ones((4, 8), np.float32)}
    t1 = {"w": np.ones((8, 4), np.float32)}
    store = HostParamStore({"e": np.ones((2,), np.float32)}, [t0, t1])
    assert not store.homogeneous
    assert store.layouts[1].unflatten(store.masters[1])["w"].shape == (8, 4)


def test_stream_eval_and_state_dict():
    model = _toy_lm()
    params = model.init(jax.random.key(0))
    e = _engine(model, params, **_stream_cfg())
    ev0 = float(e.eval_batch(_batch(seed=3)))
    for s in range(3):
        e.train_batch(batch=_batch(seed=3))
    assert float(e.eval_batch(_batch(seed=3))) < ev0
    sd = e.module_state_dict()
    assert "tok_embed" in sd and "layers" in sd
    # eager whole-model loss on the consolidated params agrees with eval
    loss = float(model.loss(
        jax.tree_util.tree_map(jnp.asarray, sd), _batch(seed=3)))
    np.testing.assert_allclose(loss, float(e.eval_batch(_batch(seed=3))),
                               rtol=1e-5)


def test_stream_three_call_api_raises():
    model = _toy_lm()
    params = model.init(jax.random.key(0))
    e = _engine(model, params, **_stream_cfg())
    with pytest.raises(NotImplementedError, match="train_batch"):
        e.forward(_batch())


def test_stream_requires_streamable_model():
    from unit.simple_model import SimpleModel
    m = SimpleModel(hidden_dim=16)
    p = m.init(jax.random.key(0))
    with pytest.raises(ValueError, match="layer-streamable"):
        _engine(m, p, **_stream_cfg())


def test_zero_init_remote_device_hosts_params():
    """zero.Init(remote_device='cpu') keeps the tree host-resident
    (reference partition_parameters.py:539) and the engine consumes it."""
    from deepspeed_tpu.runtime.zero.partition_parameters import Init
    model = _toy_lm()
    with Init(remote_device="cpu", dtype=jnp.float32) as ctx:
        params = ctx.init(model.init, jax.random.key(0))
    assert all(isinstance(x, np.ndarray)
               for x in jax.tree_util.tree_leaves(params))
    e = _engine(model, params, **_stream_cfg())
    losses = [float(e.train_batch(batch=_batch(seed=0))) for _ in range(3)]
    assert losses[-1] < losses[0]


# ----------------------------------------------------------------------
# sharded streaming (multi-device mesh)
# ----------------------------------------------------------------------
def test_stream_sp_matches(mesh_sp):
    """sp×fsdp mesh + ulysses attention: sequence-parallel activations
    under streamed host-resident params — trajectory matches the
    device-resident offload engine (round-4 verdict, next #10)."""
    model = _toy_lm(attn_impl="ulysses")
    params = model.init(jax.random.key(0))
    e_res = _engine(model, params, **_offload_cfg())
    eng, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config=base_config(**_stream_cfg(stage=3)), mesh=mesh_sp,
        tp_rules=model.tp_rules())
    assert eng._param_stream is not None
    for seed in range(2):
        b = _batch(bsz=8, seed=seed)
        l1 = float(e_res.train_batch(batch=b))
        l2 = float(eng.train_batch(batch=b))
        np.testing.assert_allclose(l1, l2, rtol=5e-5)


def test_stream_sharded_uploads_match(mesh_2d):
    """tp×fsdp mesh: uploaded working sets carry tail-aligned tp specs +
    fsdp; trajectory matches the single-device stream."""
    model = _toy_lm()
    params = model.init(jax.random.key(0))
    e_plain = _engine(model, params, **_stream_cfg())
    eng, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config=base_config(**_stream_cfg(stage=3)), mesh=mesh_2d,
        tp_rules=model.tp_rules())
    assert eng._param_stream is not None
    for seed in range(2):
        b = _batch(bsz=8, seed=seed)
        l1 = float(e_plain.train_batch(batch=b))
        l2 = float(eng.train_batch(batch=b))
        np.testing.assert_allclose(l1, l2, rtol=5e-5)


def test_stream_checkpoint_zero_to_fp32(tmp_path):
    """Offline consolidation: a param-stream checkpoint converts to the
    full nested fp32 tree WITHOUT the model (the .meta.json sidecar
    carries the structure) — the reference zero_to_fp32 workflow for
    beyond-HBM training runs."""
    from deepspeed_tpu.checkpoint.zero_to_fp32 import (
        get_fp32_state_dict_from_zero_checkpoint)
    model = _toy_lm()
    params = model.init(jax.random.key(0))
    e = _engine(model, params, **_stream_cfg())
    for s in range(2):
        e.train_batch(batch=_batch(seed=s))
    e.save_checkpoint(str(tmp_path), tag="ck")
    got = get_fp32_state_dict_from_zero_checkpoint(str(tmp_path), tag="ck")
    want = e._param_stream.params_tree()
    gotf = {jax.tree_util.keystr(p): np.asarray(x)
            for p, x in jax.tree_util.tree_flatten_with_path(got)[0]}
    for p, x in jax.tree_util.tree_flatten_with_path(want)[0]:
        k = jax.tree_util.keystr(p)
        if not jnp.issubdtype(np.asarray(x).dtype, jnp.floating):
            continue
        np.testing.assert_allclose(gotf[k], np.asarray(x, np.float32),
                                   rtol=1e-6, err_msg=k)


def test_stream_checkpoint_zero_to_fp32_moe(tmp_path):
    """Heterogeneous (MoE list) stacks consolidate per-layer."""
    from deepspeed_tpu.checkpoint.zero_to_fp32 import (
        get_fp32_state_dict_from_zero_checkpoint)
    model = _toy_lm(moe_num_experts=4, moe_top_k=1, moe_layer_freq=2)
    params = model.init(jax.random.key(0))
    e = _engine(model, params, **_stream_cfg())
    e.train_batch(batch=_batch(seed=0))
    e.save_checkpoint(str(tmp_path), tag="ck")
    got = get_fp32_state_dict_from_zero_checkpoint(str(tmp_path), tag="ck")
    want = e._param_stream.params_tree()
    np.testing.assert_allclose(
        np.asarray(got["layers"][1]["moe"]["wg"]),
        np.asarray(want[1]["moe"]["wg"])
        if isinstance(want, list) else
        np.asarray(want["layers"][1]["moe"]["wg"]), rtol=1e-6)
