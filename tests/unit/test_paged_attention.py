"""Paged KV-cache attention tests: paged path must reproduce the dense
ring-buffer decode attention on ragged batches."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.decode_attention import (KVCache, decode_attention,
                                                init_cache, update_cache)
from deepspeed_tpu.ops.paged_attention import (PagedAllocator, append_paged,
                                               init_paged_cache,
                                               paged_decode_attention,
                                               prefill_paged)

H, HKV, D, PAGE = 4, 2, 8, 4


def _rand(shape, seed=0):
    return jnp.asarray(np.random.default_rng(seed).normal(size=shape),
                       jnp.float32)


def test_allocator_reuse_and_tables():
    al = PagedAllocator(num_pages=10, page_size=PAGE, max_pages_per_seq=4)
    p0 = al.allocate("a", 9)     # 3 pages
    p1 = al.allocate("b", 4)     # 1 page
    assert len(p0) == 3 and len(p1) == 1 and not set(p0) & set(p1)
    table = al.block_table(["a", "b"])
    assert table.shape == (2, 4)
    np.testing.assert_array_equal(table[0, :3], p0)
    al.free_sequence("a")
    assert al.can_allocate(3)
    p2 = al.allocate("c", 12)
    assert set(p2) <= set(p0) | set(al.free) | set(p2)  # reused pool
    al.extend("b", 6)            # crosses into a second page
    assert len(al.seq_pages["b"]) == 2


def test_paged_matches_dense_single_seq():
    """Prefill + several decode steps, non-trivial page permutation."""
    B, T0 = 1, 6
    al = PagedAllocator(num_pages=8, page_size=PAGE, max_pages_per_seq=4)
    al.free = [5, 1, 7, 2, 0, 3, 6, 4]  # force scattered pages
    al.allocate(0, T0)

    dense = init_cache(B, 16, HKV, D, jnp.float32)
    paged = init_paged_cache(8, PAGE, HKV, D, jnp.float32)
    lengths = jnp.zeros((B,), jnp.int32)

    k0, v0 = _rand((B, T0, HKV, D), 1), _rand((B, T0, HKV, D), 2)
    dense = update_cache(dense, k0, v0)
    tables = jnp.asarray(al.block_table([0]))
    paged, lengths = prefill_paged(paged, tables, lengths, k0, v0)

    for step in range(5):
        al.extend(0, T0 + step + 1)
        tables = jnp.asarray(al.block_table([0]))
        q = _rand((B, 1, H, D), 10 + step)
        k1, v1 = _rand((B, 1, HKV, D), 20 + step), _rand((B, 1, HKV, D),
                                                         30 + step)
        dense = update_cache(dense, k1, v1)
        paged, lengths = append_paged(paged, tables, lengths, k1, v1)
        ref = decode_attention(q, dense)
        got = paged_decode_attention(q, paged, tables, lengths)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)


def test_paged_ragged_batch():
    """Two sequences of different lengths batched together — the case the
    dense ring buffer cannot express without padding to max length."""
    al = PagedAllocator(num_pages=16, page_size=PAGE, max_pages_per_seq=4)
    al.allocate("s0", 3)
    al.allocate("s1", 11)
    paged = init_paged_cache(16, PAGE, HKV, D, jnp.float32)
    tables = jnp.asarray(al.block_table(["s0", "s1"]))
    lengths = jnp.zeros((2,), jnp.int32)

    # per-sequence prefill with different lengths: pad the short one and
    # overwrite lengths afterwards (host orchestration)
    k = _rand((2, 11, HKV, D), 1)
    v = _rand((2, 11, HKV, D), 2)
    al.extend("s0", 11)  # scratch pages so padded writes land somewhere
    tables = jnp.asarray(al.block_table(["s0", "s1"]))
    paged, _ = prefill_paged(paged, tables, lengths, k, v)
    lengths = jnp.asarray([3, 11], jnp.int32)

    q = _rand((2, 1, H, D), 3)
    got = paged_decode_attention(q, paged, tables, lengths)

    # reference: each sequence independently with a dense cache
    for b, L in enumerate((3, 11)):
        dense = init_cache(1, 16, HKV, D, jnp.float32)
        dense = update_cache(dense, k[b:b + 1, :L], v[b:b + 1, :L])
        ref = decode_attention(q[b:b + 1], dense)
        np.testing.assert_allclose(np.asarray(got[b:b + 1]), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)


def test_gqa_paged():
    B, T0 = 2, 5
    al = PagedAllocator(num_pages=8, page_size=PAGE, max_pages_per_seq=2)
    al.allocate(0, T0)
    al.allocate(1, T0)
    paged = init_paged_cache(8, PAGE, HKV, D, jnp.float32)
    tables = jnp.asarray(al.block_table([0, 1]))
    lengths = jnp.zeros((B,), jnp.int32)
    k, v = _rand((B, T0, HKV, D), 1), _rand((B, T0, HKV, D), 2)
    paged, lengths = prefill_paged(paged, tables, lengths, k, v)
    q = _rand((B, 1, H, D), 3)   # H=4 query heads over HKV=2 (GQA)
    out = paged_decode_attention(q, paged, tables, lengths)
    assert out.shape == (B, 1, H, D)
    dense = init_cache(B, 8, HKV, D, jnp.float32)
    dense = update_cache(dense, k, v)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(decode_attention(q, dense)),
                               rtol=1e-5, atol=1e-6)
