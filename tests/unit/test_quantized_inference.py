"""Weight-only quantized inference tests.

Parity model: reference MoQ / ``GroupQuantizer`` int8 inference path
(``module_inject/replace_module.py:152``) and the quantizer op unit tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                              TransformerConfig)


def _model_and_params():
    cfg = TransformerConfig.tiny(hidden_size=64, n_heads=4)
    model = CausalTransformerLM(cfg)
    return model, model.init(jax.random.key(0))


def _ids(vocab=256, B=2, S=16):
    return np.random.default_rng(0).integers(0, vocab, (B, S))


def test_int8_weights_stored_and_outputs_close():
    model, params = _model_and_params()
    ref_engine = deepspeed_tpu.init_inference(model=model, params=params,
                                              dtype="fp32")
    ids = _ids()
    ref_logits, _ = ref_engine.forward(ids)

    from deepspeed_tpu.parallel import groups
    groups.reset_mesh()
    q_engine = deepspeed_tpu.init_inference(
        model=model, params=params, dtype="fp32",
        quant={"enabled": True, "num_bits": 8, "group_size": 64})
    assert q_engine._quantized
    # big weights live as int8 + scales
    wq = q_engine.params["layers"]["wq"]
    assert isinstance(wq, dict) and wq["qv"].dtype == jnp.int8
    q_logits, _ = q_engine.forward(ids)
    # int8 groupwise: same argmax on most positions, close logits
    ref, got = np.asarray(ref_logits), np.asarray(q_logits)
    agree = (ref.argmax(-1) == got.argmax(-1)).mean()
    assert agree > 0.9, f"argmax agreement {agree}"
    assert np.abs(ref - got).mean() < 0.1


def test_int8_dtype_string_enables_quant():
    model, params = _model_and_params()
    engine = deepspeed_tpu.init_inference(model=model, params=params,
                                          dtype="int8")
    assert engine._quantized
    assert engine.dtype == jnp.bfloat16   # int8 stores, bf16 computes
    out = engine.generate(_ids(), max_new_tokens=4)
    assert out.shape == (2, 20)


def test_quantized_memory_footprint():
    model, params = _model_and_params()
    engine = deepspeed_tpu.init_inference(
        model=model, params=params, dtype="fp32",
        quant={"enabled": True, "num_bits": 8, "group_size": 64})

    def nbytes(tree):
        return sum(x.size * x.dtype.itemsize
                   for x in jax.tree_util.tree_leaves(tree)
                   if hasattr(x, "dtype"))
    fp32_bytes = nbytes(params)
    q_bytes = nbytes(engine.params)
    assert q_bytes < fp32_bytes * 0.45   # ~4x smaller + scales overhead
