"""BERT MLM convergence sanity (reference ``tests/model/BingBertSquad``
role: an encoder fine-tuning-style task must converge end-to-end).
Run explicitly with ``pytest tests/model -m nightly``.
"""

import jax
import numpy as np
import pytest

import deepspeed_tpu

pytestmark = pytest.mark.nightly


def test_tiny_bert_mlm_memorizes():
    from deepspeed_tpu.models.bert import BertConfig, BertEncoder

    cfg = BertConfig(vocab_size=64, hidden_size=64, n_layers=2, n_heads=4,
                     max_seq_len=32)
    model = BertEncoder(cfg)
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.key(0)),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": 2}})
    dp = engine._config.data_parallel_size
    rng = np.random.default_rng(0)
    B = max(4, dp)
    ids = rng.integers(4, 64, (B, 32))
    masked = ids.copy()
    mask_pos = rng.random((B, 32)) < 0.3
    masked[mask_pos] = 3                      # [MASK]
    labels = np.where(mask_pos, ids, -100)    # only masked positions count
    batch = {"input_ids": masked, "labels": labels}
    losses = [float(engine.train_batch(batch=batch)) for _ in range(60)]
    assert np.isfinite(losses).all()
    assert losses[-1] < 0.2, f"MLM did not converge: {losses[::10]}"
