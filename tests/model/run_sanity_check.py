#!/usr/bin/env python3
"""End-to-end model sanity runner (reference ``tests/model/run_sanity_check.py``
role): runs the convergence suite that the default unit run excludes.

Usage::

    python tests/model/run_sanity_check.py          # all model sanity tests
"""

import os
import subprocess
import sys

if __name__ == "__main__":
    here = os.path.dirname(os.path.abspath(__file__))
    rc = subprocess.call(
        [sys.executable, "-m", "pytest", here, "-m", "nightly", "-v"]
        + sys.argv[1:],
        cwd=os.path.dirname(here))
    print("SANITY CHECK " + ("PASSED" if rc == 0 else "FAILED"))
    sys.exit(rc)
