"""End-to-end convergence sanity (reference ``tests/model/Megatron_GPT2``
``run_sanity_check.py`` role): a small GPT must actually CONVERGE — drive
the loss below an absolute threshold on a memorizable corpus — not merely
"loss went down".  Run explicitly with ``pytest tests/model -m nightly``.
"""

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.transformer import (CausalTransformerLM,
                                              TransformerConfig)

pytestmark = pytest.mark.nightly


def _corpus(vocab, batch, seq, seed=0):
    """A fixed periodic corpus: predictable continuation, memorizable."""
    rng = np.random.default_rng(seed)
    base = rng.integers(0, vocab, seq + 1)
    rows = [np.roll(base, r)[:seq] for r in range(batch)]
    return {"input_ids": np.stack(rows)}


@pytest.mark.parametrize("ds_over", [
    {"zero_optimization": {"stage": 0}},
    {"zero_optimization": {"stage": 3}, "mesh": {"tp": 2, "fsdp": 4}},
])
def test_tiny_gpt_memorizes(ds_over):
    cfg = TransformerConfig.tiny(hidden_size=64, n_heads=4, n_layers=2,
                                 vocab_size=64)
    model = CausalTransformerLM(cfg)
    ds = {"train_micro_batch_size_per_gpu": 1,
          "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
          "bf16": {"enabled": True},
          **ds_over}
    kw = {}
    if "mesh" in ds_over:
        kw["tp_rules"] = model.tp_rules()
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.key(0)),
        config=ds, **kw)
    dp = engine._config.data_parallel_size
    batch = _corpus(64, max(4, dp), 32)
    losses = [float(engine.train_batch(batch=batch)) for _ in range(60)]
    assert np.isfinite(losses).all()
    assert losses[-1] < 0.15, f"did not converge: {losses[::10]}"


def test_fp16_loss_scale_survives_convergence():
    """Dynamic loss scaling must not prevent convergence (overflow steps
    skip, scale adapts — the reference's fp16 sanity path)."""
    cfg = TransformerConfig.tiny(hidden_size=64, n_heads=4, n_layers=2,
                                 vocab_size=64)
    model = CausalTransformerLM(cfg)
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=model.init(jax.random.key(0)),
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
                "fp16": {"enabled": True, "initial_scale_power": 24},
                "zero_optimization": {"stage": 1}})
    dp = engine._config.data_parallel_size
    batch = _corpus(64, max(4, dp), 32, seed=1)
    losses = [float(engine.train_batch(batch=batch)) for _ in range(60)]
    assert np.isfinite(losses).all()
    assert losses[-1] < 0.3, f"fp16 did not converge: {losses[::10]}"
    # the loss-scale automaton actually engaged (scale is finite, > 0)
    assert float(engine.state.loss_scale.cur_scale) > 0
