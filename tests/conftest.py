"""Test harness: 8 virtual CPU devices (the TPU translation of the
reference's ``tests/unit/common.py DistributedExec`` fork-N-procs fixture —
see SURVEY.md §4: single-process multi-device JAX with device-count fakery).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_parallel_state():
    from deepspeed_tpu.parallel import groups
    groups.reset_mesh()
    yield
    groups.reset_mesh()


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """Drop compiled executables once a module's tests are done.  Every
    jitted program holds mmap'd code + constants; across the full suite
    the process otherwise accumulates tens of thousands of maps and
    segfaults into ``vm.max_map_count`` on default-tuned hosts.  Live
    arrays are untouched and later modules simply recompile."""
    yield
    jax.clear_caches()


@pytest.fixture
def mesh_1d():
    """All 8 devices on the fsdp axis (pure ZeRO topology)."""
    from deepspeed_tpu.parallel.topology import TopologyConfig, build_mesh
    return build_mesh(TopologyConfig())


@pytest.fixture
def mesh_2d():
    """4-way fsdp × 2-way tp."""
    from deepspeed_tpu.parallel.topology import TopologyConfig, build_mesh
    return build_mesh(TopologyConfig(tp=2))


@pytest.fixture
def mesh_sp():
    """4-way fsdp × 2-way sp (sequence parallelism)."""
    from deepspeed_tpu.parallel.topology import TopologyConfig, build_mesh
    return build_mesh(TopologyConfig(sp=2))
