"""Package build: python extension for host-native ops + console scripts.

Parity: reference ``setup.py`` (op pre-compile via ``DS_BUILD_OPS`` becomes
``DSTPU_BUILD_OPS`` — when set, the C++ host ops (cpu_adam, aio) are
compiled at install time instead of first use; Pallas ops need no AOT step,
XLA compiles them).
"""

import os

from setuptools import find_packages, setup

ext_modules = []
cmdclass = {}

if os.environ.get("DSTPU_BUILD_OPS", "0") == "1":
    from setuptools import Extension
    ext_modules = [
        Extension(
            "deepspeed_tpu.ops.native_ext",
            sources=["deepspeed_tpu/ops/csrc/cpu_adam.cpp",
                     "deepspeed_tpu/ops/csrc/aio.cpp"],
            extra_compile_args=["-O3", "-fopenmp", "-march=native",
                                "-std=c++17"],
            extra_link_args=["-fopenmp"],
        )
    ]

setup(
    name="deepspeed_tpu",
    version="0.1.0",
    description="TPU-native training/inference framework with DeepSpeed's "
                "capabilities (JAX/XLA/Pallas)",
    packages=find_packages(include=["deepspeed_tpu", "deepspeed_tpu.*"]),
    include_package_data=True,
    scripts=["bin/deepspeed", "bin/ds_report", "bin/ds_bench"],
    entry_points={
        "console_scripts": [
            "ds_report=deepspeed_tpu.env_report:cli_main",
        ],
    },
    install_requires=["jax", "numpy", "optax", "flax", "orbax-checkpoint"],
    python_requires=">=3.10",
    ext_modules=ext_modules,
)
